"""Black-box flight recorder + cross-node postmortem forensics
(ISSUE 9 tentpole).

Covers: ring wraparound/overwrite semantics over preallocated slots,
the no-allocation hot-path guard (tracemalloc over a taped record
loop), default-on recording of real traffic (message heads land in
every node's ring), pressure gauges flowing through the PR 7 metrics
pump + the status console's pressure column, exactly-one-dump-per-
alert-transition e2e (HealthEngine → Control.FLIGHT_DUMP broadcast →
every node dumps once under one incident id, the alert record carries
the paths), the operator wire trigger (Ctrl.FLIGHT_DUMP), postmortem
assembly of a 3-role chain with rebased clocks, the disabled path
(GEOMX_FLIGHT=0 constructs nothing), and the slow acceptance e2e
(SIGKILL a global-shard primary mid-training → ≥3 nodes' dumps
assemble into one timeline naming the dead node, the stalled
round/shard and the promotion).
"""

import glob
import json
import os
import threading
import time
import tracemalloc

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.common import Ctrl
from geomx_tpu.obs.flight import FlightEv, FlightRecorder, dump_path
from geomx_tpu.obs.postmortem import assemble, report_text
from geomx_tpu.transport.message import Domain


def _cfg(parties=1, workers=1, **kw):
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=workers), **kw)


def _wait_for(pred, timeout=15.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _run_rounds(sim, rounds, tids=(0,), n=32):
    ws = sim.all_workers()
    for _ in range(rounds):
        for w in ws:
            for t in tids:
                w.push(t, np.ones(n, np.float32))
        for w in ws:
            for t in tids:
                w.pull_sync(t)
            w.wait_all()


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_wraparound_overwrite():
    """A full ring overwrites its OLDEST slots in place: the decode
    returns exactly the last ``cap`` events in order, the total
    recorded count keeps climbing, and the column arrays are never
    reallocated."""
    rec = FlightRecorder("node:0", cap=8)
    ids = (id(rec._t), id(rec._code), id(rec._a), id(rec._peer))
    for i in range(20):
        rec.record(FlightEv.SEND, a=i, t=float(i))
    assert rec._n == 20
    evs = rec.events()
    assert len(evs) == 8
    assert [e["a"] for e in evs] == list(range(12, 20))
    assert all(e["ev"] == "SEND" for e in evs)
    # same preallocated arrays after 2.5x wraparound
    assert ids == (id(rec._t), id(rec._code), id(rec._a), id(rec._peer))
    # partial fill decodes only what was recorded
    rec2 = FlightRecorder("node:1", cap=8)
    rec2.record(FlightEv.FENCE, a=7, peer="worker:0@p0", note="x")
    evs2 = rec2.events()
    assert len(evs2) == 1
    assert evs2[0]["ev"] == "FENCE" and evs2[0]["peer"] == "worker:0@p0"


def test_record_hot_path_no_allocation():
    """The guard the tentpole promises: a taped record() loop retains
    (effectively) no memory — preallocated slots only, no per-event
    dict/list/str construction on the hot path."""
    rec = FlightRecorder("node:0", cap=1024)
    peer = "server:0@p0"  # call sites pass existing refs, never build
    for i in range(2048):  # warm: wrap the ring, touch every slot
        rec.record(FlightEv.SEND, a=5, b=1, c=4096, d=7, peer=peer)
    tracemalloc.start()
    base, _ = tracemalloc.get_traced_memory()
    for i in range(5000):
        rec.record(FlightEv.SEND, a=5, b=1, c=4096, d=7, peer=peer)
    cur, _ = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    grown = cur - base
    assert grown < 16 * 1024, \
        f"record() retained {grown}B over 5000 events — the hot path " \
        "is allocating per event"
    assert rec._n == 7048


# ---------------------------------------------------------------------------
# default-on recording of real traffic + pressure plumbing
# ---------------------------------------------------------------------------

def test_default_on_records_message_heads_and_rounds():
    """Default config: every node's postoffice carries a recorder, the
    van taps stamp SEND/RECV heads, and the servers stamp round
    open/complete — the always-on evidence trail."""
    sim = Simulation(_cfg(parties=2))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(32, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 2)
        for s, po in sim.offices.items():
            assert po.flight is not None, s
            assert po.van.flight is po.flight
        gs_evs = sim.offices["global_server:0"].flight.events()
        names = {e["ev"] for e in gs_evs}
        assert {"SEND", "RECV", "ROUND_OPEN", "ROUND_COMPLETE"} <= names
        completes = [e for e in gs_evs if e["ev"] == "ROUND_COMPLETE"]
        assert completes[-1]["b"] == 2  # key_rounds rides the event
        ls_evs = sim.offices["server:0@p0"].flight.events()
        sends = [e for e in ls_evs if e["ev"] == "SEND"]
        # peers recorded as-is, decoded to strings at dump time
        assert any(e["peer"] == "global_server:0" for e in sends)
        assert any(e["ev"] == "ROUND_OPEN" and e["note"] == "wan_push"
                   for e in ls_evs)
    finally:
        sim.shutdown()


def test_pressure_gauges_flow_through_pump_and_console():
    """sample_pressure sets the lock_wait_s / lane_depth /
    van_sendq_depth / codec_pool_busy registry gauges; the metrics pump
    ships them, and the status console's pressure column renders
    them."""
    from geomx_tpu.obs.state import render_text

    sim = Simulation(_cfg(parties=1, enable_obs=True, obs_interval_s=0.0))
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 1, n=8)
        sim.pump_metrics()
        mc = sim.metrics_collector
        for node in ("server:0@p0", "global_server:0"):
            for key in ("lock_wait_s", "lane_depth", "codec_pool_busy",
                        "van_sendq_depth"):
                v = mc.value(node, key)
                assert isinstance(v, (int, float)), (node, key, v)
        # every node ships at least the van send-queue depth
        assert isinstance(mc.value("worker:0@p0", "van_sendq_depth"),
                          (int, float))
        st = sim.cluster_state()
        assert "lock_wait_s" in st["shards"][0]["pressure"]
        assert "lane_depth" in st["parties"][0]["pressure"]
        txt = render_text(st)
        assert "press[" in txt
        # PRESSURE events landed in the ring too
        evs = sim.offices["server:0@p0"].flight.events()
        notes = {e["note"] for e in evs if e["ev"] == "PRESSURE"}
        assert {"lock_wait_s", "lane_depth", "van_sendq_depth",
                "codec_pool_busy"} <= notes
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# dump triggers
# ---------------------------------------------------------------------------

def test_exactly_one_dump_per_alert_transition(tmp_path, monkeypatch):
    """A HealthEngine FIRING transition broadcasts Control.FLIGHT_DUMP:
    every node dumps exactly once under the shared incident id (ticks
    while still firing add nothing; rebroadcasts dedup), and the alert
    record carries the dump paths.  A second transition is a second
    incident with its own dumps."""
    monkeypatch.setenv("GEOMX_OBS_DIR", str(tmp_path))
    sim = Simulation(_cfg(parties=1, enable_obs=True, obs_interval_s=0.0,
                          obs_flight_cooldown_s=0.0))
    try:
        mc, eng = sim.metrics_collector, sim.health
        n_nodes = len(sim.offices)
        mc.ingest({"node": "global_server:9", "boot": 7, "t_mono": 1.0,
                   "metrics": {"global_server:9.replication_lag_s": 120.0},
                   "stats": {}})
        recs = eng.tick(now=10.0)
        fired = [r for r in recs if r["state"] == "firing"]
        assert len(fired) == 1
        flight = fired[0]["data"].get("flight")
        assert flight and flight["dir"] == str(tmp_path)
        assert len(flight["paths"]) == n_nodes

        def n_dumps():
            return len(glob.glob(str(tmp_path / "flight_*.json")))

        assert _wait_for(lambda: n_dumps() == n_nodes), \
            (n_dumps(), n_nodes)
        # still firing: no new record, no new dumps
        assert not eng.tick(now=11.0)
        time.sleep(0.2)
        assert n_dumps() == n_nodes
        # a dump parses and names the incident
        body = json.load(open(glob.glob(
            str(tmp_path / "flight_global_scheduler*"))[0]))
        assert body["incident"] == flight["incident"]
        assert body["events"], "scheduler dumped an empty ring"
        # recover, then fire again: a NEW incident, one more dump each
        mc.ingest({"node": "global_server:9", "boot": 7, "t_mono": 2.0,
                   "metrics": {"global_server:9.replication_lag_s": 0.1},
                   "stats": {}})
        eng.tick(now=12.0)
        assert n_dumps() == n_nodes  # recovery transition: no dump
        mc.ingest({"node": "global_server:9", "boot": 7, "t_mono": 3.0,
                   "metrics": {"global_server:9.replication_lag_s": 200.0},
                   "stats": {}})
        recs = eng.tick(now=20.0)
        flight2 = [r for r in recs if r["state"] == "firing"][0][
            "data"]["flight"]
        assert flight2["incident"] != flight["incident"]
        assert _wait_for(lambda: n_dumps() == 2 * n_nodes)
    finally:
        sim.shutdown()


def test_flight_dump_cooldown_suppresses_flapping(tmp_path, monkeypatch):
    """Default cooldown: a (rule, subject) re-firing inside
    obs_flight_cooldown_s captures NO new incident — the first firing
    already holds the evidence window, and a flapping warn rule must
    not flood the dump dir."""
    monkeypatch.setenv("GEOMX_OBS_DIR", str(tmp_path))
    sim = Simulation(_cfg(parties=1, enable_obs=True, obs_interval_s=0.0,
                          obs_flight_cooldown_s=60.0))
    try:
        mc, eng = sim.metrics_collector, sim.health
        n_nodes = len(sim.offices)

        def flap(lag, now):
            mc.ingest({"node": "global_server:9", "boot": 7,
                       "t_mono": now,
                       "metrics": {"global_server:9.replication_lag_s":
                                   lag},
                       "stats": {}})
            return eng.tick(now=now)

        first = flap(120.0, 10.0)
        assert first[0]["data"].get("flight")
        assert _wait_for(lambda: len(glob.glob(
            str(tmp_path / "flight_*.json"))) == n_nodes)
        flap(0.1, 12.0)              # recover
        refire = flap(150.0, 15.0)   # re-fire inside the window
        assert refire and "flight" not in refire[0]["data"]
        time.sleep(0.2)
        assert len(glob.glob(str(tmp_path / "flight_*.json"))) == n_nodes
        # past the cooldown the next firing is a fresh incident
        flap(0.1, 30.0)
        beyond = flap(150.0, 80.0)
        assert beyond[0]["data"].get("flight")
        assert _wait_for(lambda: len(glob.glob(
            str(tmp_path / "flight_*.json"))) == 2 * n_nodes)
    finally:
        sim.shutdown()


def test_operator_flight_dump_over_the_wire(tmp_path):
    """Ctrl.FLIGHT_DUMP at the scheduler (the status console's
    --dump-flight) broadcasts the snapshot and answers with the dir +
    expected paths — no GEOMX_OBS_DIR needed when the request names the
    dir."""
    sim = Simulation(_cfg(parties=1))
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 1, n=8)
        kv = sim.worker(0, 0)
        reply = kv.worker.send_cmd(
            sim.topology.global_scheduler(), Ctrl.FLIGHT_DUMP,
            body={"dir": str(tmp_path)}, domain=Domain.GLOBAL)
        assert reply["ok"] and reply["incident"] == "operator-1"
        assert reply["nodes"] == len(sim.offices)
        assert _wait_for(lambda: len(glob.glob(
            str(tmp_path / "flight_*.json"))) == len(sim.offices))
        assert sim.state_service.flight_requests == 1
        # the assembler reads the operator dumps like any others
        res = assemble(str(tmp_path))
        assert sorted(res["nodes"]) == sorted(sim.offices)
        assert not res["dead"]
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# postmortem assembly
# ---------------------------------------------------------------------------

def test_postmortem_three_role_chain_rebased_clocks(tmp_path):
    """Three dumps with DIFFERENT local clocks (worker knows only its
    party scheduler; the offset to the global clock chains through the
    dual-homed local server) assemble into one causally ordered
    timeline: send-before-recv holds after rebasing even though the
    raw local timestamps are wildly out of order."""
    gname, sname, wname = ("global_scheduler:0", "server:0@p0",
                           "worker:0@p0")
    # server: +50s to global, +20s to psched => psched->global = +30
    # worker: +10s to psched => worker->global = 10 + 30 = +40
    offs = {gname: {}, sname: {gname: 50.0, "scheduler:0@p0": 20.0},
            wname: {"scheduler:0@p0": 10.0}}
    # causal chain on the GLOBAL clock: 100.0 -> 100.5 -> 101 -> 101.5
    chains = {
        wname: [(60.0, FlightEv.SEND, sname)],       # 60+40 = 100
        sname: [(50.5, FlightEv.RECV, wname),        # 50.5+50 = 100.5
                (51.0, FlightEv.SEND, gname)],       # 51+50 = 101
        gname: [(101.5, FlightEv.RECV, sname)],      # its clock IS global
    }
    topo = [gname, sname, wname]
    for node, evs in chains.items():
        rec = FlightRecorder(node, cap=16)
        for t, code, peer in evs:
            rec.record(code, c=8, peer=peer, t=t)
        body = rec.snapshot()
        body.update({"clock_offsets": offs[node], "topology": topo,
                     "boot": 1})
        with open(dump_path(str(tmp_path), node, "test"), "w") as f:
            json.dump(body, f)
    res = assemble(str(tmp_path))
    assert res["clock_offsets_s"][sname] == pytest.approx(50.0)
    assert res["clock_offsets_s"][wname] == pytest.approx(40.0)
    tl = [(e["node"], e["ev"]) for e in res["timeline"]]
    assert tl == [(wname, "SEND"), (sname, "RECV"), (sname, "SEND"),
                  (gname, "RECV")]
    ts = [e["t"] for e in res["timeline"]]
    assert ts == sorted(ts)
    assert ts[-1] - ts[0] == pytest.approx(1.5)
    assert not res["dead"]
    assert "3 node(s)" in report_text(res)


def test_postmortem_names_dead_node_from_survivor_rings(tmp_path):
    """A plan node that left NO dump is reported dead, with the last
    instant a survivor heard from it (its SIGKILL leaves exactly this
    evidence shape)."""
    gname, sname = "global_scheduler:0", "server:0@p0"
    dead = "global_server:1"
    topo = [gname, sname, dead, "global_server:0"]
    rec = FlightRecorder(sname, cap=32)
    rec.record(FlightEv.SEND, c=100, peer=dead, t=5.0)
    rec.record(FlightEv.RECV, c=64, peer=dead, t=6.0)   # last heard
    rec.record(FlightEv.SEND, c=100, peer=dead, t=9.0)  # unanswered
    body = rec.snapshot()
    body.update({"clock_offsets": {gname: 0.0}, "topology": topo})
    with open(dump_path(str(tmp_path), sname, "exit"), "w") as f:
        json.dump(body, f)
    rec0 = FlightRecorder("global_server:0", cap=32)
    rec0.record(FlightEv.ROUND_COMPLETE, a=1, b=4, t=8.0)
    body0 = rec0.snapshot()
    body0.update({"clock_offsets": {gname: 0.0}, "topology": topo})
    with open(dump_path(str(tmp_path), "global_server:0", "exit"),
              "w") as f:
        json.dump(body0, f)
    res = assemble(str(tmp_path))
    d = {e["node"]: e for e in res["dead"]}
    assert dead in d and "global_scheduler:0" in d  # no dump either
    assert d[dead]["last_heard_t"] == pytest.approx(6.0)
    assert d[dead]["last_heard_by"] == sname
    # the dead holder names its shard stalled even with no events of
    # its own in any ring window
    assert res["shards"][1]["stalled"]
    assert res["shards"][1]["dead_holder"] == dead
    assert not res["shards"][0]["stalled"]
    txt = report_text(res)
    assert f"DEAD: {dead}" in txt
    assert "shard 1: STALLED" in txt


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_disabled_flight_constructs_nothing(tmp_path):
    """GEOMX_FLIGHT=0 / enable_flight=False: no recorder on any
    postoffice, no van tap, no sampler thread, no files — and the
    health engine's dump trigger degrades to a silent no-op."""
    sim = Simulation(_cfg(parties=1, enable_flight=False,
                          enable_obs=True, obs_interval_s=0.0))
    try:
        for s, po in sim.offices.items():
            assert po.flight is None, s
            assert po.van.flight is None, s
        names = {t.name for t in threading.enumerate()}
        assert not any(n.startswith("flight-sampler") for n in names)
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 1, n=8)
        sim.pump_metrics()
        # no pressure gauges ship without a recorder sampling them
        assert sim.metrics_collector.value("server:0@p0",
                                           "lane_depth") is None
        # an alert transition dumps nothing (no recorder plane)
        os.environ["GEOMX_OBS_DIR"] = str(tmp_path)
        try:
            sim.metrics_collector.ingest(
                {"node": "global_server:9", "boot": 1, "t_mono": 1.0,
                 "metrics": {"global_server:9.replication_lag_s": 99.0},
                 "stats": {}})
            recs = sim.health.tick(now=10.0)
        finally:
            del os.environ["GEOMX_OBS_DIR"]
        assert recs and "flight" not in recs[0]["data"]
        assert not glob.glob(str(tmp_path / "flight_*.json"))
        assert sim.dump_flight(str(tmp_path)) == []
    finally:
        sim.shutdown()


def test_flight_env_default_and_override(monkeypatch):
    """Config default follows GEOMX_FLIGHT (on unless set falsy); an
    explicitly constructed value wins over the env."""
    assert Config().enable_flight is True
    monkeypatch.setenv("GEOMX_FLIGHT", "0")
    assert Config().enable_flight is False
    assert Config(enable_flight=True).enable_flight is True
    monkeypatch.delenv("GEOMX_FLIGHT")
    assert Config(enable_flight=False).enable_flight is False
    with pytest.raises(ValueError):
        Config(flight_events=4)


# ---------------------------------------------------------------------------
# acceptance e2e (slow): SIGKILL a shard primary -> assembled postmortem
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.failover
def test_postmortem_of_killed_shard_primary_e2e(tmp_path, monkeypatch):
    """The ISSUE 9 acceptance shape, in-proc: kill global shard 1's
    primary mid-training; the round-stall alert broadcasts a flight
    dump (same incident window on every surviving node), the exit
    dumps follow, and the assembler's report names the dead node, the
    stalled round/shard, and the subsequent promotion — from ≥3
    distinct nodes' rings."""
    monkeypatch.setenv("GEOMX_OBS_DIR", str(tmp_path))
    from geomx_tpu.kvstore.keys import encode_tensor

    cfg = Config(
        topology=Topology(num_parties=2, workers_per_party=1,
                          num_global_servers=2, num_standby_globals=2),
        enable_obs=True, obs_interval_s=0.0,
        request_retry_s=0.4, heartbeat_interval_s=0.05,
        heartbeat_timeout_s=0.4, replicate_every=1, retry_backoff_cap=2,
        obs_stall_min_s=0.3, obs_stall_factor=2.0)
    sim = Simulation(cfg)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(16, np.float32))
            w.init(1, np.zeros(16, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        for _ in range(3):
            _run_rounds(sim, 1, tids=(0, 1), n=16)
            sim.pump_metrics()
            sim.health.tick()
        sb1 = sim.standby_globals[1]
        k1 = encode_tensor(1, 16, 2)[0].ps_key
        assert _wait_for(lambda: k1 in sb1.store), "replication stalled"
        sim.kill_global_server(1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            _run_rounds(sim, 1, tids=(0,), n=16)
            sim.pump_metrics()
            sim.health.tick()
            if sim.health.active_alerts():
                break
            time.sleep(0.05)
        assert sim.health.active_alerts(), "round stall never alerted"
        assert _wait_for(lambda: not sb1.is_standby), "promotion stalled"
        _run_rounds(sim, 1, tids=(1,), n=16)  # replays at the standby
        sim.dump_flight(str(tmp_path))  # the survivors' exit dumps
        dumped_nodes = {json.load(open(p))["node"] for p in
                        glob.glob(str(tmp_path / "flight_*.json"))}
        assert len(dumped_nodes) >= 3
        assert "global_server:1" not in dumped_nodes  # SIGKILL = no dump
        res = assemble(str(tmp_path))
        assert {d["node"] for d in res["dead"]} == {"global_server:1"}
        assert res["dead"][0]["last_heard_t"] is not None
        assert res["shards"][1]["stalled"]
        assert res["shards"][1]["dead_holder"] == "global_server:1"
        assert res["shards"][1]["stalled_round"] > 0
        assert not res["shards"][0]["stalled"]
        promos = [e for e in res["transitions"] if e["ev"] == "PROMOTE"]
        assert any(e.get("peer") == "standby_global:1" for e in promos)
        txt = report_text(res)
        assert "DEAD: global_server:1" in txt
        assert "shard 1: STALLED" in txt
        assert "standby_global:1" in txt
    finally:
        sim.shutdown()
