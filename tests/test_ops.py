"""Pallas on-chip codec kernels (interpret mode on CPU; the same kernels
compile for TPU)."""

import jax.numpy as jnp
import numpy as np

from geomx_tpu.ops import dequantize_2bit_tpu, dgc_update_tpu, quantize_2bit_tpu


def test_quantize_2bit_roundtrip_and_residual():
    rng = np.random.default_rng(0)
    n = 5000  # forces padding
    g = jnp.asarray(rng.standard_normal(n), jnp.float32)
    r0 = jnp.zeros(n, jnp.float32)
    packed, r1 = quantize_2bit_tpu(g, r0, threshold=0.5, interpret=True)
    assert packed.dtype == jnp.uint8
    dec = dequantize_2bit_tpu(packed, n, threshold=0.5, interpret=True)

    gn = np.asarray(g)
    expected = np.zeros(n, np.float32)
    expected[gn > 0.5] = 0.5
    expected[gn < -0.5] = -0.5
    np.testing.assert_allclose(np.asarray(dec), expected)
    # residual feedback: r1 = g - emitted
    np.testing.assert_allclose(np.asarray(r1), gn - expected, rtol=1e-6)
    # mass conservation across repeated rounds
    total = np.asarray(dec).copy()
    r = r1
    for _ in range(5):
        packed, r = quantize_2bit_tpu(jnp.zeros(n, jnp.float32), r,
                                      threshold=0.5, interpret=True)
        total += np.asarray(dequantize_2bit_tpu(packed, n, threshold=0.5,
                                                interpret=True))
    resid = np.asarray(r)
    np.testing.assert_allclose(total + resid, gn, atol=1e-5)


def test_wire_size_is_16x():
    from geomx_tpu.ops.quantize import LANES, _QROWS

    n = _QROWS * LANES  # one full block: no padding overhead
    g = jnp.ones(n, jnp.float32)
    packed, _ = quantize_2bit_tpu(g, jnp.zeros(n, jnp.float32),
                                  interpret=True)
    assert packed.nbytes == n // 4  # 2 bits/elem = 16x vs f32


def test_dgc_update_matches_reference():
    rng = np.random.default_rng(1)
    n = 3000
    v = jnp.asarray(rng.standard_normal(n), jnp.float32)
    u = jnp.asarray(rng.standard_normal(n), jnp.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32)
    vo, uo = dgc_update_tpu(v, u, g, momentum=0.9, interpret=True)
    v_ref = 0.9 * np.asarray(v) + np.asarray(g)
    np.testing.assert_allclose(np.asarray(vo), v_ref, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(uo), np.asarray(u) + v_ref,
                               rtol=1e-4, atol=1e-6)
