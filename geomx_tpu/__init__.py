"""geomx_tpu — a TPU-native geo-distributed training framework.

A from-scratch reimplementation of the capabilities of GeoMX
(hierarchical parameter server for multi-datacenter training) designed
for TPU hardware: JAX/XLA/pjit for the compute path, `jax.lax` collectives
over ICI for intra-datacenter aggregation, and a host-side hierarchical
parameter-server runtime for the WAN tier.

Layer map (bottom → top), mirroring the reference architecture
(see SURVEY.md §1; reference = INET-RC/GeoMX):

- ``transport``  — message fabric (Van): in-proc sim + TCP, fault injection,
                   priority send queues, DGT multi-channel scheduling.
- ``ps``         — parameter-server runtime: Postoffice (node table,
                   barriers), Customer (request tracking), KVWorker/KVServer.
- ``kvstore``    — the HiPS logic: worker-side dist kvstore, the two-tier
                   hierarchical server, sync modes (FSA/MixedSync/HFA).
- ``compression``— wire codecs: FP16, 2-bit quant, Bi-Sparse top-k, MPQ.
- ``sched``      — P3 priority propagation, TSEngine overlay, DGT.
- ``overlap``    — staged worker loop: per-stage push during backward /
                   per-stage pull gating in forward (the reference's
                   engine-driven compute/comm overlap, rebuilt for XLA).
- ``parallel``   — TPU mesh parallelism: DP/TP/SP shardings, ring attention.
- ``models``     — reference workloads (CNN) + flagship transformer.
- ``optim``      — optimizers including DCASGD.
- ``utils``      — profiler, metrics, logging.
"""

__version__ = "0.1.0"

from geomx_tpu.core.config import Config, Role, Topology  # noqa: F401
