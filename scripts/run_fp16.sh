#!/usr/bin/env bash
# Acceptance config: fp16 (mirrors the reference scripts/cpu/run_fp16.sh)
exec "$(dirname "$0")/run_cluster.sh" --compression fp16
