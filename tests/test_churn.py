"""Elastic membership under preemption churn (ISSUE 13): the graceful
drain protocol (Control.PREEMPT_NOTICE — notice → flush → leave →
immediate fold, never a heartbeat-expiry stall), the concurrent
membership-transition matrix (two parties folding in one global round,
a join landing during a drain, a notice racing its own heartbeat
expiry), ESync planner churn hygiene, the seeded churn orchestrator
(geomx_tpu/chaos), and the churn_storm health rule.  Fast tests are
tier-1 and run under BOTH the legacy threads harness and the
lightweight reactor dispatch path; the 24-party spot-churn soak with
loss parity against an uninterrupted control is slow + scale.
"""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Group, NodeId, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.utils.metrics import system_counter, system_gauge

pytestmark = pytest.mark.chaos

# the membership-transition tests shake under the thread-per-endpoint
# harness AND the shared-reactor serial-dispatch path — concurrency
# windows differ between them by construction
TRANSPORTS = [pytest.param(False, id="threads"),
              pytest.param(True, id="reactor")]


def _cfg(parties=1, workers=2, **kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("heartbeat_timeout_s", 2.0)
    kw.setdefault("enable_preempt", True)
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=workers), **kw)


def _wait_for(pred, timeout=20.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _freeze_heartbeats(po):
    """Silence one node's heartbeat source (both transport modes) —
    the node stays functional, only its liveness signal stops."""
    if po._hb_task is not None:
        po._hb_task.cancel()
        po._hb_task = None
    if po._hb_thread is not None:
        po._hb_stop.set()


@pytest.mark.parametrize("lightweight", TRANSPORTS)
def test_notice_drains_worker_without_eviction(lightweight):
    """The drain protocol end to end: a noticed worker flushes, leaves,
    and is folded out IMMEDIATELY — drain latency a small fraction of
    the eviction timeout, the eviction monitor never fires, rounds and
    barriers continue on the survivor set."""
    sim = Simulation(_cfg(), lightweight=lightweight)
    try:
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(8, np.float32))
        w0.set_optimizer({"type": "sgd", "lr": 1.0})
        for w in (w0, w1):
            w.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -2 * np.ones(8, np.float32))
        for w in (w0, w1):
            w.wait_all()

        # a barrier entered while w1 is still a member must release
        # when the graceful leave drops it from barrier accounting
        released = []

        def barrier():
            w0.po.barrier(Group.WORKERS, timeout=30)
            released.append(True)

        th = threading.Thread(target=barrier)
        th.start()

        reply = sim.notice_worker(0, 1)
        assert reply and reply["ok"], reply
        # acceptance: notice→member-folded well under the eviction
        # window (the whole point — no heartbeat-expiry stall)
        timeout = sim.config.heartbeat_timeout_s
        assert reply["latency_s"] < 0.25 * timeout, reply
        ls = sim.local_servers[0]
        assert ls.left_workers == 1
        assert ls.evicted_workers == 0
        assert w1.preempt_drains == 1

        th.join(30)
        assert released, "graceful leave did not release the barrier"

        # the survivor's next round completes alone — no stall window
        w0.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -3 * np.ones(8, np.float32))
        # ... and the monitor stayed quiet the whole time
        time.sleep(3 * sim.config.heartbeat_interval_s)
        assert sim.eviction_monitors[0].evictions == 0
        assert "worker:1@p0" not in sim.eviction_monitors[0]._evicted
        # drain visible in the flight ring (postmortem attribution)
        evs = [e for e in w1.po.flight.events()
               if e["note"] == "preempt_drain"]
        assert evs, "drain left no flight event"
    finally:
        sim.shutdown()


@pytest.mark.parametrize("lightweight", TRANSPORTS)
def test_notice_races_own_heartbeat_expiry_notice_wins(lightweight):
    """The worker's liveness signal dies at notice time and the drain
    is SLOW (a parked pull holds it open past the heartbeat timeout):
    the draining-member hold must keep the eviction monitor quiet for
    the drain window, so the graceful leave — not an eviction — ends
    the membership.  The monitor must also not double-fold afterward."""
    sim = Simulation(_cfg(heartbeat_timeout_s=0.4, preempt_drain_s=1.2),
                     lightweight=lightweight)
    try:
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(4, np.float32))
        w0.set_optimizer({"type": "sgd", "lr": 1.0})
        for w in (w0, w1):
            w.push(0, np.ones(4, np.float32))
        w0.pull_sync(0)
        for w in (w0, w1):
            w.wait_all()
        # open a round only w1 contributes to, and park w1's pull on it
        # — the drain's flush now blocks until the leave itself folds
        # the round, holding the drain open across the expiry window
        w1.push(0, np.ones(4, np.float32))
        got = []
        w1.pull(0, lambda t, a: got.append(a))
        _freeze_heartbeats(w1.po)  # liveness dies WITH the notice

        t0 = time.monotonic()
        reply = sim.notice_worker(0, 1, timeout=10.0)
        drained_at = time.monotonic() - t0
        assert reply and reply["ok"], reply
        # the drain provably outlived the heartbeat timeout...
        assert drained_at > sim.config.heartbeat_timeout_s, drained_at
        ls = sim.local_servers[0]
        mon = sim.eviction_monitors[0]
        # ...yet the notice won: graceful leave, never an eviction
        assert ls.left_workers == 1
        assert ls.evicted_workers == 0
        assert mon.evictions == 0
        assert mon.notice_holds >= 1
        # the leave folded the round w1 held open — its pull serves
        assert _wait_for(lambda: bool(got), 5)

        # double-fold guard: a late EVICT for the already-left member
        # must not decrement the target again (the monitor's own RPC
        # machinery, so the reply routes like a real sweep's would)
        target_before = ls._workers_target
        reply = mon._rpc(sim.topology.server(0), Control.EVICT,
                         {"node": "worker:1@p0", "boot": 1},
                         Domain.LOCAL)
        assert reply is not None and reply["evicted"] is False, reply
        assert ls._workers_target == target_before
        assert ls.evicted_workers == 0
    finally:
        sim.shutdown()


@pytest.mark.parametrize("lightweight", TRANSPORTS)
def test_two_parties_fold_in_same_global_round(lightweight):
    """Concurrent party-level transitions: one local server drains on
    notice while another dies ungracefully — BOTH fold out of the same
    mid-flight global round, the surviving party's round completes,
    and both parties later rejoin (replacement warm boot → unfold)."""
    sim = Simulation(_cfg(parties=3, workers=1, heartbeat_timeout_s=0.5,
                          request_retry_s=0.5),
                     lightweight=lightweight)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(8, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        for w in ws:
            w.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(ws[0].pull_sync(0),
                                   -np.ones(8, np.float32))
        for w in ws:
            w.wait_all()

        # open the next global round with only party 0's contribution
        ws[0].push(0, np.ones(8, np.float32))
        # party 1 drains gracefully; party 2 dies ungracefully — the
        # two folds land on the same open round
        reply = sim.notice_local_server(1)
        assert reply and reply["ok"], reply
        sim.kill_local_server(2)
        # the round completes on the lone survivor (notice fold is
        # immediate; party 2's fold lands after its expiry)
        np.testing.assert_allclose(ws[0].pull_sync(0),
                                   -2 * np.ones(8, np.float32))
        gs = sim.global_servers[0]
        assert _wait_for(lambda: gs.num_contributors == 1, 10)
        assert gs.party_folds == 2
        assert sim.recovery_monitor.preempt_folds == 1
        assert sim.recovery_monitor.party_folds == 1  # only the crash

        # the noticed party's host is reclaimed; replacements come up
        sim.kill_local_server(1)
        time.sleep(2.5 * sim.config.heartbeat_timeout_s)
        sim.restart_local_server(1)
        sim.restart_local_server(2)
        assert _wait_for(
            lambda: sim.recovery_monitor.party_unfolds == 2, 40), \
            "parties never folded back in"
        for w in ws:
            w.push(0, np.ones(8, np.float32))
        outs = [w.pull_sync(0) for w in ws]
        np.testing.assert_allclose(outs[0], -3 * np.ones(8, np.float32))
        np.testing.assert_allclose(outs[0], outs[1])
        np.testing.assert_allclose(outs[0], outs[2])
    finally:
        sim.shutdown()


@pytest.mark.parametrize("lightweight", TRANSPORTS)
def test_join_lands_during_anothers_drain(lightweight):
    """A worker joins while another member's drain is in flight (held
    open by a parked pull): the join and the leave serialize through
    the membership seq — the final target is exactly (survivors +
    joiner), and the joiner trains."""
    sim = Simulation(_cfg(preempt_drain_s=1.0), lightweight=lightweight)
    try:
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(4, np.float32))
        w0.set_optimizer({"type": "sgd", "lr": 1.0})
        for w in (w0, w1):
            w.push(0, np.ones(4, np.float32))
        w0.pull_sync(0)
        for w in (w0, w1):
            w.wait_all()
        # hold w1's drain open: a round only w1 contributed to
        w1.push(0, np.ones(4, np.float32))
        got = []
        w1.pull(0, lambda t, a: got.append(a))

        replies = []
        th = threading.Thread(target=lambda: replies.append(
            sim.notice_worker(0, 1, timeout=10)))
        th.start()
        time.sleep(0.05)  # let the notice land; the drain is now held
        #                   open by w1's parked pull
        # the join lands while the drain is still flushing
        wj = sim.add_worker(0)
        wj.init(0, np.zeros(4, np.float32))  # publish shapes (no-op
        #                                       server-side)
        th.join(15)
        assert replies and replies[0] and replies[0]["ok"], replies

        ls = sim.local_servers[0]
        assert ls._workers_target == 2  # w0 + joiner, never 1 or 3
        assert wj.num_workers == 2
        # whichever way the join/leave interleaved, close any round the
        # transition left partially counted before the clean round below
        st = ls._keys[0]
        if st.accum is not None:
            w0.push(0, np.ones(4, np.float32))
            assert _wait_for(lambda: st.accum is None, 10)
        # the post-transition group trains: both members' round lands
        for w in (w0, wj):
            w.push(0, np.ones(4, np.float32))
        a = w0.pull_sync(0)
        b = wj.pull_sync(0)
        np.testing.assert_allclose(a, b)
    finally:
        sim.shutdown()


def test_preempt_disabled_is_legacy_default():
    """Default-off guard: without ``enable_preempt`` no notice hook is
    registered anywhere — a PREEMPT_NOTICE on the wire is ignored, the
    member stays, and the legacy graceful-leave / eviction paths are
    untouched.  ``notice_worker`` refuses loudly."""
    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=2),
                            heartbeat_interval_s=0.05,
                            heartbeat_timeout_s=2.0))
    try:
        assert not sim.config.enable_preempt  # the default
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(4, np.float32))
        with pytest.raises(AssertionError, match="enable_preempt"):
            sim.notice_worker(0, 1)
        # raw wire notice: nothing consumes it, nothing drains
        sim.offices["scheduler:0@p0"].van.send(Message(
            recipient=NodeId.parse("worker:1@p0"),
            control=Control.PREEMPT_NOTICE, domain=Domain.LOCAL,
            request=True, body={"token": "t-guard"}))
        time.sleep(0.3)
        ls = sim.local_servers[0]
        assert "worker:1@p0" in ls._members
        assert ls.left_workers == 0
        assert not w1.preempt_noticed.is_set()
        # the legacy graceful leave still behaves exactly as before
        w1.leave_party()
        assert ls.left_workers == 1 and ls._workers_target == 1
    finally:
        sim.shutdown()


def test_esync_planner_forgets_departed_worker():
    """ESync churn hygiene: a departed straggler's stale step estimate
    must leave the reach-time target with it — before the fix it stayed
    in the max forever and pinned every survivor's assignment high."""
    from geomx_tpu.sched.esync import EsyncState

    st = EsyncState(min_steps=1, max_steps=64)
    st.report("fast", step_s=0.01, comm_s=0.0, max_steps=64)
    st.report("slow", step_s=0.50, comm_s=0.0, max_steps=64)
    # the straggler sets the target: the fast worker fills the window
    assert st.plan()["fast"] >= 40
    assert st.drop("slow") is True
    assert st.workers() == ["fast"]
    # target collapsed to the fast worker's own reach time
    assert st.plan()["fast"] == st.min_steps
    assert st.drop("slow") is False  # idempotent

    # server-level wiring: the graceful leave folds the member out of
    # the planner too (the fold IS the replan trigger)
    sim = Simulation(_cfg())
    try:
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(4, np.float32))
        assert w0.esync_report(0.01, 0.0, max_steps=64) >= 1
        assert w1.esync_report(0.50, 0.0, max_steps=64) == 1
        # the straggler inflates the fast worker's assignment...
        assert w0.esync_report(0.01, 0.0, max_steps=64) >= 40
        srv = sim.local_servers[0]
        assert sorted(srv._esync.workers()) == ["worker:0@p0",
                                                "worker:1@p0"]
        w1.leave_party()
        assert srv._esync.workers() == ["worker:0@p0"]
        # ...and the fold deflates it back to min_steps
        assert w0.esync_report(0.01, 0.0, max_steps=64) == 1
    finally:
        sim.shutdown()


def test_churn_orchestrator_scripted_seeded_and_attributed():
    """The orchestrator executes a SEEDED tape (same seed → same tape),
    respects the min-survivor floor, counts every injected event in the
    churn_* registry family, and stamps each into the flight recorder
    so postmortems can attribute stalls to injected faults."""
    from geomx_tpu.chaos import ChurnOrchestrator, ChurnPhase, ChurnPlan

    phases = (ChurnPhase(2.0, departure_rate=2.5, join_rate=1.5,
                         notice_fraction=1.0),)
    assert (ChurnPlan(phases=phases, seed=11).schedule()
            == ChurnPlan(phases=phases, seed=11).schedule())
    assert (ChurnPlan(phases=phases, seed=11).schedule()
            != ChurnPlan(phases=phases, seed=12).schedule())

    sim = Simulation(_cfg(parties=2, workers=2, heartbeat_timeout_s=0.6,
                          preempt_drain_s=2.0))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        plan = ChurnPlan(phases=phases, seed=11,
                         min_workers_per_party=1,
                         max_workers_per_party=3)
        orch = ChurnOrchestrator(sim, plan)
        orch.run()  # inline: deterministic completion
        stats = orch.stats()
        assert stats["transitions"] > 0, "the tape injected nothing"
        # counters match the executed tape exactly
        gsched = str(sim.topology.global_scheduler())
        assert (system_counter(f"{gsched}.churn_notices").value
                == stats["notices"])
        assert (system_counter(f"{gsched}.churn_joins").value
                == stats["joins"])
        # min-survivor floor held at every point
        for p, ranks in orch._alive.items():
            assert len(ranks) >= plan.min_workers_per_party, (p, ranks)
        assert stats["survivors"] >= 2 * plan.min_workers_per_party
        # every injected event is in the global scheduler's flight ring
        churn_evs = [e for e in sim.offices[gsched].flight.events()
                     if e["ev"] == "CHURN"]
        assert len(churn_evs) == len(orch.events)
        # a noticed departure is NEVER an eviction
        for mon in sim.eviction_monitors:
            assert not (set(mon._evicted) & orch.noticed), (
                mon._evicted, orch.noticed)
        # graceful drains measured and fast
        if stats["drain_latency_s"]:
            med = stats["drain_latency_s"][
                len(stats["drain_latency_s"]) // 2]
            assert med < 0.25 * sim.config.heartbeat_timeout_s
        orch.stop()
    finally:
        sim.shutdown()


def test_churn_storm_health_rule_fires_and_surfaces():
    """The churn_storm rule: transition rate over the collector window
    past the bound fires `cluster`; the orchestrator's survivor gauge
    at the floor fires `survivor_floor` (critical) — both visible in
    the status console's active-alert list."""
    cfg = _cfg(workers=1, heartbeat_interval_s=0.0, enable_obs=True,
               obs_interval_s=0.0, obs_churn_storm=10)
    sim = Simulation(cfg)
    try:
        gsched = str(sim.topology.global_scheduler())
        system_counter(f"{gsched}.churn_notices").inc(2)
        system_counter(f"{gsched}.churn_ungraceful_kills").inc(1)
        sim.pump_metrics()
        sim.health.tick()  # one series point: the rule stays quiet
        system_counter(f"{gsched}.churn_notices").inc(12)
        system_counter(f"{gsched}.churn_ungraceful_kills").inc(6)
        sim.pump_metrics()
        recs = sim.health.tick()
        storm = [r for r in recs if r["rule"] == "churn_storm"
                 and r["subject"] == "cluster"]
        assert storm and storm[0]["state"] == "firing", recs
        # survivor floor: gauges the orchestrator ships
        system_gauge(f"{gsched}.churn_survivors").set(2)
        system_gauge(f"{gsched}.churn_min_survivors").set(2)
        sim.pump_metrics()
        recs = sim.health.tick()
        floor = [r for r in recs if r["subject"] == "survivor_floor"]
        assert floor and floor[0]["severity"] == "critical", recs
        # surfaced in the live cluster state (python -m geomx_tpu.status)
        active = (sim.cluster_state().get("health") or {}).get("active")
        assert any(a["rule"] == "churn_storm" for a in active), active
    finally:
        sim.shutdown()


def test_training_loops_break_at_step_boundary_on_notice():
    """run_worker finishes the in-flight step and stops when the notice
    lands — the drain's 'finish your step, then flush' contract."""
    import jax

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker

    sim = Simulation(_cfg(workers=1, heartbeat_interval_s=0.0))
    try:
        kv = sim.all_workers()[0]
        x, y = synthetic_classification(n=64, shape=(8, 8, 1), seed=0)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
        it = ShardedIterator(x, y, 8, 0, 1)

        def log(step, _l, _a):
            if step == 2:
                kv.preempt_noticed.set()

        hist = run_worker(kv, params, grad_fn, it, 50,
                          barrier_init=False, log_fn=log)
        assert len(hist) == 3, "loop did not break at the boundary"
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# the 24-party spot-churn soak (slow + scale; pytest -m scale)
# ---------------------------------------------------------------------------


def _quad_loop(kv, name, target, state, stop_all, errs):
    """Free-running FSA round loop on a quadratic objective: push
    grad((w-t)^2)/n + per-worker noise, pull, record loss.  Bounded
    waits so a killed worker's thread exits instead of wedging."""
    rng = np.random.RandomState(abs(hash(name)) % (2 ** 31))
    w = state.get("w")
    if w is None:
        w = kv.pull_sync(0) if state.get("bootstrap") else \
            np.zeros_like(target)
    try:
        while not stop_all.is_set() and not kv.preempt_noticed.is_set():
            g = (w - target + rng.normal(0, 0.01, target.shape)
                 .astype(np.float32)) / kv.num_workers
            kv.push(0, g)
            got = []
            ts = kv.pull(0, lambda t, a: got.append(a))
            deadline = time.monotonic() + 120
            while not got:
                try:
                    kv.worker.customer.wait(ts, timeout=0.5)
                except TimeoutError:
                    if kv.po.van.killed:
                        raise RuntimeError("killed")
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"{name}: round stuck >120s")
            w = got[0]
            state["w"] = w
            state["loss"] = float(np.mean((w - target) ** 2))
            state["rounds"] = state.get("rounds", 0) + 1
    except Exception as e:  # noqa: BLE001 — killed workers land here
        state["stopped"] = True  # pushes no more (the teardown's
        #                          per-party leave_global gate)
        errs.append((name, repr(e)))
        return
    state["stopped"] = True
    if not kv.preempt_noticed.is_set():
        # orderly end: leave so siblings' final rounds fold complete
        try:
            kv.wait_all()
        except Exception:
            pass
        try:
            kv.leave_party(timeout=15)
        except Exception as e:  # noqa: BLE001
            errs.append((name, f"leave: {e!r}"))


def _run_soak(parties, rounds_target, churn_plan=None):
    from geomx_tpu.chaos import ChurnOrchestrator

    cfg = _cfg(parties=parties, workers=2, heartbeat_timeout_s=0.6,
               request_retry_s=0.5, preempt_drain_s=5.0,
               lightweight=True,
               # at 24 parties the scheduler's ring sees ~1k message
               # heads/s — a soak-length window needs a deeper ring or
               # early injected events are overwritten before the
               # attribution check reads them
               flight_events=1 << 16)
    sim = Simulation(cfg, lightweight=True)
    dim = 128
    target = np.linspace(-1.0, 1.0, dim).astype(np.float32)
    stop_all = threading.Event()
    errs: list = []
    states = {}
    threads = []
    orch = None
    try:
        ws = sim.all_workers()
        for kv in ws:
            kv.init(0, np.zeros(dim, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.3})

        def start(kv, bootstrap=False):
            name = str(kv.po.node)
            st = states.setdefault(name, {"bootstrap": bootstrap})
            th = threading.Thread(
                target=_quad_loop, args=(kv, name, target, st,
                                         stop_all, errs),
                name=f"soak-{name}", daemon=True)
            threads.append(th)
            th.start()

        for kv in ws:
            start(kv)
        observer = "worker:0@p0"
        if churn_plan is not None:
            orch = ChurnOrchestrator(
                sim, churn_plan,
                spawn=lambda kv: (kv.init(0, np.zeros(dim, np.float32)),
                                  start(kv, bootstrap=True)),
                protect={observer}).start()
            orch.join(churn_plan.duration_s + 120)
            assert not orch._thread.is_alive(), "orchestrator wedged"
        # train until the protected observer saw rounds_target rounds
        assert _wait_for(
            lambda: states[observer].get("rounds", 0) >= rounds_target,
            timeout=300), (states[observer], errs)
        stop_all.set()
        # orderly wind-down: parties finish at DIFFERENT global rounds,
        # and a party that stopped pushing stalls the global FSA round
        # for everyone else — so as each party's workers exit their
        # loops, that party withdraws from the global tier
        # (leave_global), folding the survivors' final rounds complete
        left_global = set()
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            by_party = {}
            for name, st in states.items():
                p = int(name.split("@p")[1])
                by_party.setdefault(p, []).append(
                    st.get("stopped", False))
            for p, flags in by_party.items():
                if p not in left_global and all(flags):
                    left_global.add(p)
                    try:
                        sim.local_servers[p].leave_global(timeout=10)
                    except Exception:
                        pass  # dead/folded server: nothing to withdraw
            if not any(th.is_alive() for th in threads):
                break
            time.sleep(0.05)
        for th in threads:
            th.join(max(0.1, deadline - time.monotonic()))
        stuck = [th.name for th in threads if th.is_alive()]
        assert not stuck, f"permanently-stuck rounds: {stuck} ({errs})"
        return sim, orch, states, errs
    except BaseException:
        stop_all.set()
        if orch is not None:
            orch.stop()
        sim.shutdown()
        raise


@pytest.mark.slow
@pytest.mark.scale
def test_spot_churn_soak_24_parties_loss_parity():
    """Acceptance (ISSUE 13): a 24-party lightweight-reactor soak under
    a seeded Poisson ChurnPlan — mixed notices, ungraceful kills, joins
    and local-server preemptions, ≥20 membership transitions — must
    complete with loss parity vs an uninterrupted control, zero
    permanently-stuck rounds, every injected event attributable in the
    flight-recorder timeline, graceful drains well under the eviction
    window, and no noticed worker ever evicted."""
    from geomx_tpu.chaos import ChurnPhase, ChurnPlan

    parties, rounds_target = 24, 40

    # ---- control: same fleet, nobody preempted -------------------------
    sim, _, states, errs = _run_soak(parties, rounds_target)
    try:
        control_loss = states["worker:0@p0"]["loss"]
        assert not errs, errs
        assert np.isfinite(control_loss)
    finally:
        sim.shutdown()

    # ---- churn run -----------------------------------------------------
    plan = ChurnPlan(
        phases=(
            # a preemption wave: mostly-graceful departures + arrivals
            ChurnPhase(6.0, departure_rate=1.6, join_rate=1.0,
                       notice_fraction=0.6, server_kill_rate=0.15,
                       server_restart_s=1.5),
            # a harsher tail: more ungraceful kills
            ChurnPhase(6.0, departure_rate=1.4, join_rate=1.0,
                       notice_fraction=0.35),
        ),
        seed=13, min_workers_per_party=1, max_workers_per_party=3)
    sim, orch, states, errs = _run_soak(parties, rounds_target,
                                        churn_plan=plan)
    try:
        stats = orch.stats()
        churn_loss = states["worker:0@p0"]["loss"]
        # loss parity with the uninterrupted control: both runs sit at
        # the quadratic's noise floor — churn must not knock training
        # off it
        assert np.isfinite(churn_loss)
        assert abs(churn_loss - control_loss) < 0.05, (
            churn_loss, control_loss)
        assert churn_loss < 0.05, churn_loss
        # the plan actually churned: ≥20 executed transitions, mixed
        assert stats["transitions"] >= 20, stats
        assert stats["notices"] > 0 and stats["ungraceful_kills"] > 0 \
            and stats["joins"] > 0, stats
        # every injected event attributable in the flight timeline
        gsched = str(sim.topology.global_scheduler())
        churn_evs = [e for e in sim.offices[gsched].flight.events()
                     if e["ev"] == "CHURN"]
        assert len(churn_evs) == len(orch.events), (
            len(churn_evs), len(orch.events))
        by_note = {}
        for e in churn_evs:
            by_note[e["note"]] = by_note.get(e["note"], 0) + 1
        assert by_note.get("churn_notice", 0) == stats["notices"]
        assert by_note.get("churn_join", 0) == stats["joins"]
        # drain latency: notice→folded median a small fraction of the
        # eviction timeout, and a noticed worker NEVER fired the monitor
        drains = stats["drain_latency_s"]
        assert drains, "no graceful drain completed"
        med = drains[len(drains) // 2]
        assert med < 0.25 * sim.config.heartbeat_timeout_s, drains
        for mon in sim.eviction_monitors:
            overlap = set(mon._evicted) & orch.noticed
            assert not overlap, overlap
        # only killed workers errored out of their loops
        bad = [n for n, _ in errs
               if n not in orch.killed and n not in orch.noticed]
        assert not bad, (bad, errs)
        # the observer made continuous progress: zero stuck rounds
        assert states["worker:0@p0"]["rounds"] >= rounds_target
    finally:
        sim.shutdown()
