"""Checker: lock discipline for the ``*_locked`` naming convention and
the cross-module lock-acquisition-order graph.

Three rules (docs/static-analysis.md has the full catalog):

``unguarded-locked-call``
    Every call to a ``*_locked`` method must be *dominated* by holding a
    lock: the call sits lexically inside a ``with <lock>`` block (or an
    explicit ``.acquire()``/``.release()`` bracket) in the same
    function, OR the enclosing function is itself ``*_locked`` (the
    caller-chain contract), OR the enclosing function documents the
    chain (an ``assert`` mentioning the lock / a docstring saying the
    caller holds it), OR the call is in ``__init__`` (construction is
    pre-concurrent: the object has not been published to another thread
    yet).

``lock-order-cycle``
    Nested lock acquisitions define edges ``outer → inner`` (direct
    nesting, plus bounded-depth interprocedural edges: a call made
    under lock L into a function that acquires M yields L → M).  A
    cycle in that graph is a deadlock waiting for the right
    interleaving.  Reentrant self-edges (RLock re-entry) are ignored.

``drain-under-lock``
    ``ShardExecutor.drain()`` quiesces the merge lanes, and lane work
    takes key stripes — draining while holding the stripe lock (or the
    all-stripes barrier) is a lock-order inversion against every lane
    thread, so any ``.drain(`` call lexically under a ``with <lock>``
    is flagged.

Lock identity is name-based: a ``with`` item acquires a lock when its
expression is ``self.<attr>`` / ``<obj>.<attr>`` whose final attribute
looks like a lock (``*_mu``/``mu``/``*_lock``/``lock``/``*_cv``) or a
``.stripe(...)`` call on one.  Canonical lock names qualify the attr by
the class that declares it (``TcpFabric._registry_mu``), so one lock
used from several modules is one graph node.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from geomx_tpu.analysis.core import (CallGraph, Checker, Finding,
                                     FunctionInfo, Project, _attr_chain)

_LOCK_ATTR = re.compile(r"^_?(?:[a-z0-9_]*_)?(?:mu|lock|cv|mutex)$")

#: docstring phrases that document a caller-holds contract
_DOC_PHRASES = ("caller holds", "callers hold", "under the lock",
                "with the lock held", "holding the lock", "caller must hold")


def _is_lock_attr(name: str) -> bool:
    return bool(_LOCK_ATTR.match(name))


def _lock_expr_name(expr: ast.expr) -> Optional[Tuple[str, bool]]:
    """``(attr_name, is_stripe)`` when ``expr`` acquires a lock:
    ``self._mu`` → ("_mu", False); ``self._mu.stripe(k)`` → ("_mu",
    True); bare module-level ``_registry_mu`` also counts."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "stripe":
        inner = _attr_chain(expr.func.value)
        if inner:
            attr = inner.split(".")[-1]
            if _is_lock_attr(attr):
                return attr, True
        return None
    chain = _attr_chain(expr)
    if chain is None:
        return None
    attr = chain.split(".")[-1]
    if _is_lock_attr(attr):
        return attr, False
    return None


class LockDiscipline(Checker):
    name = "lock-discipline"
    description = ("*_locked calls must hold a lock; the lock-acquisition"
                   " order graph must be acyclic; no lane drain() under a"
                   " lock")

    #: interprocedural depth for the acquires* closure
    ORDER_DEPTH = 3

    def run(self, project: Project) -> List[Finding]:
        graph = CallGraph(project)
        findings: List[Finding] = []
        # canonical lock naming: attr -> declaring classes
        declared: Dict[str, List[str]] = {}
        for f in project.files:
            for ci in f.classes.values():
                for attr in ci.lock_attrs:
                    declared.setdefault(attr, []).append(ci.name)

        def canon(fn: FunctionInfo, attr: str, stripe: bool) -> str:
            owners = declared.get(attr, [])
            if fn.cls is not None and fn.cls in owners:
                owner = fn.cls
            elif len(set(owners)) == 1:
                owner = owners[0]
            else:
                owner = fn.cls or fn.module.rel
            return f"{owner}.{attr}" + (".stripe" if stripe else "")

        # per-function: direct acquisitions + per-call held-lock context
        acquires: Dict[int, Set[str]] = {}
        order_edges: Dict[Tuple[str, str], Finding] = {}
        calls_under: List[Tuple[FunctionInfo, "ast.Call", Set[str]]] = []

        for fn in project.functions:
            held_at, acq = self._scan(fn, canon)
            acquires[id(fn)] = acq
            body = fn.node
            doc = (ast.get_docstring(body) or "").lower() \
                if not isinstance(body, ast.Lambda) else ""
            documented = any(p in doc for p in _DOC_PHRASES) \
                or self._has_lock_assert(fn)
            for call in fn.calls:
                held = held_at.get(id(call.node), frozenset())
                calls_under.append((fn, call.node, set(held)))
                # rule: *_locked call must be guarded
                if call.name.endswith("_locked"):
                    guarded = (bool(held) or fn.name.endswith("_locked")
                               or fn.is_init or documented)
                    if not guarded:
                        findings.append(self.finding(
                            fn.module.rel, call.line, fn.qualname,
                            call.name,
                            f"call to {call.name}() holds no lock: not "
                            "inside a with/acquire block, the caller is "
                            "not itself *_locked, and the function "
                            "documents no caller-holds contract"))
                # rule: drain under a held lock
                if call.name == "drain" and held:
                    findings.append(self.finding(
                        fn.module.rel, call.line, fn.qualname,
                        "drain-under-lock",
                        f"lane drain() called while holding "
                        f"{sorted(held)} — lane work takes key stripes, "
                        "so draining under a lock inverts the lane "
                        "ordering and can deadlock"))
            # direct nesting edges
            for outer, inner, line in self._nesting(fn, canon):
                if outer != inner:
                    order_edges.setdefault((outer, inner), self.finding(
                        fn.module.rel, line, fn.qualname,
                        f"order:{outer}->{inner}",
                        f"acquires {inner} while holding {outer}"))

        # interprocedural order edges: call under L into g ⇒ L → each
        # lock in acquires*(g) (bounded closure)
        closure = self._acquire_closure(project, graph, acquires)
        for fn, call_node, held in calls_under:
            if not held:
                continue
            site = None
            for c in fn.calls:
                if c.node is call_node:
                    site = c
                    break
            if site is None:
                continue
            for callee in graph.resolve(fn, site):
                for inner in closure.get(id(callee), ()):
                    for outer in held:
                        if outer != inner:
                            order_edges.setdefault(
                                (outer, inner), self.finding(
                                    fn.module.rel, site.line, fn.qualname,
                                    f"order:{outer}->{inner}",
                                    f"calls {callee.qualname}() (which "
                                    f"acquires {inner}) while holding "
                                    f"{outer}"))

        findings.extend(self._cycles(order_edges))
        return findings

    # -- function-local lock tracking -------------------------------------
    def _scan(self, fn: FunctionInfo, canon):
        """Map id(call-node) -> frozenset of canonical locks lexically
        held at that call, plus the set of locks this function acquires
        anywhere."""
        held_at: Dict[int, frozenset] = {}
        acquired: Set[str] = set()

        def visit(node: ast.AST, held: frozenset):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return  # nested defs are separate functions
            new_held = held
            if isinstance(node, ast.With):
                got = []
                for item in node.items:
                    ln = _lock_expr_name(item.context_expr)
                    if ln is not None:
                        got.append(canon(fn, *ln))
                if got:
                    acquired.update(got)
                    new_held = held | frozenset(got)
                for item in node.items:
                    visit(item.context_expr, held)
                for child in node.body:
                    visit(child, new_held)
                return
            if isinstance(node, ast.Call):
                held_at[id(node)] = held
                # explicit lock.acquire() also counts as acquisition
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "acquire":
                    ln = _lock_expr_name(node.func.value)
                    if ln is not None:
                        acquired.add(canon(fn, *ln))
            for child in ast.iter_child_nodes(node):
                visit(child, new_held)

        body = fn.node.body if not isinstance(fn.node, ast.Lambda) \
            else [ast.Expr(fn.node.body)]
        for stmt in body:
            visit(stmt, frozenset())
        return held_at, acquired

    def _nesting(self, fn: FunctionInfo, canon):
        """Direct (outer, inner, line) nesting pairs inside one
        function."""
        out: List[Tuple[str, str, int]] = []

        def visit(node: ast.AST, held: List[str]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return
            if isinstance(node, ast.With):
                got = []
                for item in node.items:
                    ln = _lock_expr_name(item.context_expr)
                    if ln is not None:
                        got.append(canon(fn, *ln))
                for g in got:
                    for h in held:
                        out.append((h, g, node.lineno))
                for child in node.body:
                    visit(child, held + got)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        if not isinstance(fn.node, ast.Lambda):
            for stmt in fn.node.body:
                visit(stmt, [])
        return out

    def _has_lock_assert(self, fn: FunctionInfo) -> bool:
        if isinstance(fn.node, ast.Lambda):
            return False
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assert):
                src = ast.dump(node)
                if "_mu" in src or "lock" in src.lower():
                    return True
        return False

    # -- order graph -------------------------------------------------------
    def _acquire_closure(self, project: Project, graph: CallGraph,
                         direct: Dict[int, Set[str]]) -> Dict[int, Set[str]]:
        closure = {k: set(v) for k, v in direct.items()}
        for _ in range(self.ORDER_DEPTH):
            changed = False
            for fn in project.functions:
                acc = closure.setdefault(id(fn), set())
                before = len(acc)
                for call in fn.calls:
                    for callee in graph.resolve(fn, call):
                        acc |= closure.get(id(callee), set())
                if len(acc) != before:
                    changed = True
            if not changed:
                break
        return closure

    def _cycles(self, edges: Dict[Tuple[str, str], Finding]
                ) -> List[Finding]:
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        findings: List[Finding] = []
        seen_cycles: Set[frozenset] = set()
        # DFS cycle detection, reporting each distinct node set once
        for start in sorted(adj):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == path[0] and len(path) > 1:
                        key = frozenset(path)
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            witness = edges[(path[0], path[1])]
                            findings.append(Finding(
                                self.name, witness.path, witness.line,
                                "lock-order-cycle::" + "->".join(
                                    sorted(path)),
                                "lock acquisition order cycle: "
                                + " -> ".join(path + [path[0]])))
                    elif nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))
        return findings
