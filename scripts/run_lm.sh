#!/usr/bin/env bash
# Flagship LM acceptance: the 10.3M-param transformer through the full
# TCP topology with MPQ compression (no reference counterpart — GeoMX's
# example matrix is CNN-only; this is the TPU-native flagship workload).
# Size via GEOMX_LM_* (docs/env-vars.md).
set -euo pipefail
HERE="$(cd "$(dirname "$0")" && pwd)"
PARTIES="${PARTIES:-1}" WORKERS="${WORKERS:-1}" STEPS="${STEPS:-3}" \
  exec "$HERE/run_cluster.sh" --workload lm --compression mpq \
       --batch "${BATCH:-4}" "$@"
