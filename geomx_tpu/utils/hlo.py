"""Optimized-HLO collective audit helpers.

Shared by the MoE/tp collective-footprint tests
(tests/test_moe_collectives.py) and the multichip dryrun
(__graft_entry__.dryrun_multichip) so the regexes — including the
async-start tuple-shape handling — live in exactly one place.

HLO instruction forms handled::

    %x = f32[2,32]{1,0} all-gather(%y), ...
    %x = (f32[2,32]{1,0}, f32[2,32]{1,0}) all-gather-start(%y), ...

The sync form's shape is a single ``dtype[dims]``; the async start's is
a tuple (whose inner spaces defeat naive ``= \\S+ op(`` patterns), so
matching keys on the opcode token itself.  ``*-done`` ops are the
completion halves of starts and are not counted (that would double
count one collective).
"""

from __future__ import annotations

import re
from typing import Dict, List

COLLECTIVE_OPS = ("all-gather", "all-to-all", "all-reduce",
                  "reduce-scatter", "collective-permute")

_DTYPE_B = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
            "pred": 1, "s8": 1, "u8": 1, "f64": 8, "s64": 8,
            "c64": 8}


def _op_lines(hlo: str, op: str):
    """Instruction lines computing ``op`` (sync or async-start form)."""
    pat = re.compile(rf" {re.escape(op)}(?:-start)?\(")
    for ln in hlo.splitlines():
        if " = " in ln and pat.search(ln):
            yield ln


def collective_counts(hlo: str) -> Dict[str, int]:
    """Count collective instructions per op kind in optimized HLO text."""
    return {op: sum(1 for _ in _op_lines(hlo, op))
            for op in COLLECTIVE_OPS}


def _result_bytes(line: str) -> int:
    """Largest array in the instruction's result shape (a tuple for
    async starts — taking the max avoids double-counting the buffer
    the start form repeats)."""
    lhs = line.split(" = ", 1)[1]
    op_at = re.search(r" [a-z-]+(?:\.\d+)?\(", lhs)
    shape_txt = lhs[:op_at.start()] if op_at else lhs
    best = 0
    for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape_txt):
        n = 1
        for d in filter(None, dims.split(",")):
            n *= int(d)
        best = max(best, n * _DTYPE_B.get(dtype, 4))
    return best


def large_gathers(hlo: str, threshold_bytes: int = 16 * 1024) -> List[str]:
    """all-gather instructions whose result exceeds the threshold —
    the 'activations/dispatch got replicated' regression signal (tiny
    index/router gathers of a few KB are normal on sp meshes)."""
    return [ln.strip()[:160] for ln in _op_lines(hlo, "all-gather")
            if _result_bytes(ln) > threshold_bytes]
