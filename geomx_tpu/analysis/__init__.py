"""AST-based static-analysis suite (concurrency & protocol lint).

``python -m geomx_tpu.analysis`` runs every checker over the live tree
and exits non-zero on any finding not suppressed by
``analysis-baseline.toml``; ``tests/test_analysis.py`` pins the same
run green in tier 1.  See docs/static-analysis.md for the checker
catalog and the baseline policy.
"""

from __future__ import annotations

import pathlib
from typing import Dict, Iterable, List, Optional, Tuple, Type

from geomx_tpu.analysis.baseline import (DEFAULT_BASELINE, Baseline,
                                         BaselineError, skeleton)
from geomx_tpu.analysis.config_drift import ConfigDrift
from geomx_tpu.analysis.core import Checker, Finding, Project
from geomx_tpu.analysis.decode_bounds import DecodeBounds
from geomx_tpu.analysis.doc_drift import MetricsDoc
from geomx_tpu.analysis.lock_discipline import LockDiscipline
from geomx_tpu.analysis.reactor_blocking import ReactorBlocking
from geomx_tpu.analysis.wire_protocol import WireProtocol

#: name -> checker class, in catalog order
CHECKERS: Dict[str, Type[Checker]] = {
    c.name: c for c in (LockDiscipline, ReactorBlocking, WireProtocol,
                        ConfigDrift, MetricsDoc, DecodeBounds)
}


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def run_checkers(project: Optional[Project] = None,
                 names: Optional[Iterable[str]] = None,
                 baseline: Optional[Baseline] = None,
                 ) -> Tuple[List[Finding], List[Finding], Baseline]:
    """Run the named checkers (default: all) and split the findings by
    the baseline.  Returns ``(unsuppressed, suppressed, baseline)``."""
    if project is None:
        project = Project(repo_root())
    if baseline is None:
        baseline = Baseline.load(
            pathlib.Path(project.root) / DEFAULT_BASELINE)
    wanted = list(names) if names is not None else list(CHECKERS)
    findings: List[Finding] = []
    for name in wanted:
        if name not in CHECKERS:
            raise KeyError(
                f"unknown checker {name!r} (have: {sorted(CHECKERS)})")
        findings.extend(CHECKERS[name]().run(project))
    findings.sort(key=lambda f: (f.checker, f.path, f.line, f.key))
    fresh, eaten = baseline.filter(findings)
    return fresh, eaten, baseline


__all__ = [
    "Baseline", "BaselineError", "CHECKERS", "Checker", "Finding",
    "Project", "repo_root", "run_checkers", "skeleton",
]
