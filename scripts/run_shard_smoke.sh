#!/usr/bin/env bash
# Sharded-global-tier smoke lane: run the kvstore/failover/eviction test
# subset with the global tier forced to 2 key-range shards
# (GEOMX_GLOBAL_SHARDS shakes directly-constructed Configs too, the way
# GEOMX_SERVER_SHARDS does for the striped-merge path), so the sharded
# code path cannot silently rot while tier-1 runs single-global.
#
# Env: PYTEST_ARGS (extra pytest flags), GEOMX_GLOBAL_SHARDS (default 2)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_GLOBAL_SHARDS=${GEOMX_GLOBAL_SHARDS:-2}

exec python -m pytest -q -m 'not slow' -p no:cacheprovider \
  tests/test_kvstore.py tests/test_failover.py tests/test_eviction.py \
  tests/test_sharded_global.py tests/test_recovery.py \
  ${PYTEST_ARGS:-}
