# CPU image for pseudo-distributed / multi-host deployment of the
# framework (parity with the reference's docker surface:
# /root/reference/docker/build_on_cpu.dockerfile builds the MXNet fork;
# here the compute substrate is jax[cpu], so the image is pip-only plus
# the g++ toolchain for the native codec library).
#
#   docker build -f docker/build_on_cpu.dockerfile -t geomx-tpu:cpu .
#   docker run --rm geomx-tpu:cpu                     # runs the CNN demo
#   docker compose -f docker/compose.cluster.yml up   # full 2-party HiPS
#
# For TPU hosts use the TPU VM's base image and `pip install jax[tpu]`
# instead — everything else is identical (docs/deployment.md).

FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends build-essential make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/geomx_tpu

# jax[cpu] pulls numpy/scipy wheels; flax/optax are the model layer
RUN pip install --no-cache-dir "jax[cpu]" flax optax einops pytest

COPY geomx_tpu ./geomx_tpu
COPY scripts ./scripts
COPY examples ./examples
COPY tests ./tests
COPY pytest.ini ./

# pre-build the native codec library (ctypes loads it at import;
# the build also happens lazily at first import if skipped)
RUN make -s -C geomx_tpu/native libgeocodecs.so || true

ENV JAX_PLATFORMS=cpu \
    PYTHONUNBUFFERED=1

# default command: the reference demo — single-process simulated
# 2-party HiPS CNN run (examples/cnn.py mirrors reference
# examples/cnn.py).  The compose file overrides this with per-role
# geomx_tpu.launch commands for the real multi-process topology.
CMD ["python", "examples/cnn.py", "--steps", "8"]
