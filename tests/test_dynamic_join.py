"""Dynamic worker join (VERDICT r3 item 7; ref: ADD_NODE runtime id
assignment + node-table broadcast, ps-lite van.cc:41-112).

The build's topology is a static plan (documented divergence), so the
party SERVER owns rank assignment and the aggregation count: a new
worker registers mid-training and is folded into each key's count at
that key's next fresh aggregation round — never mid-round.
"""

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation


def _round(workers, tid, grads):
    for w, g in zip(workers, grads):
        w.push(tid, g)
    outs = [w.pull_sync(tid) for w in workers]
    for w in workers:
        w.wait_all()
    return outs


def test_worker_joins_midtraining_and_count_shifts():
    """Start 2 workers, train, add a third: the server's round count
    shifts to 3 at the next round boundary and training continues with
    all three contributions aggregated."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)

        # round 1: two workers; server applies -lr * sum = -2
        outs = _round(ws, 0, [g, g])
        np.testing.assert_allclose(outs[0], -2.0 * np.ones(4))

        # join a third worker mid-training
        w3 = sim.add_worker(0)
        assert w3.num_workers == 3
        srv = sim.local_servers[0]
        assert srv.joined_workers == 1
        # the joiner initializes its replica (no-op server-side) and
        # pulls current weights before contributing
        w3.init(0, np.zeros(4, np.float32))
        np.testing.assert_allclose(w3.pull_sync(0), -2.0 * np.ones(4))

        # round 2: THREE workers must now complete the round — if the
        # server still counted to 2, the third push would leak into a
        # phantom next round and desync every later pull
        outs = _round(ws + [w3], 0, [g, g, g])
        for o in outs:
            np.testing.assert_allclose(o, -5.0 * np.ones(4))

        # round 3: still 3
        outs = _round(ws + [w3], 0, [g, g, g])
        for o in outs:
            np.testing.assert_allclose(o, -8.0 * np.ones(4))
    finally:
        sim.shutdown()


def test_join_mid_round_extends_open_round():
    """A join landing while a round is mid-aggregation EXTENDS that
    round's target: the joiner's first pushes land in whatever round is
    open, and completing it early at the old count would leak a static
    worker's push into the next round (advisor r4).  So the open round
    waits for all three — no contribution is lost or carried over."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)

        # first worker pushes: round is now mid-aggregation (1 of 2)
        ws[0].push(0, g)
        w3 = sim.add_worker(0)  # join lands mid-round -> target 3
        ws[1].push(0, g)        # 2 of 3: round still open
        w3.init(0, np.zeros(4, np.float32))
        w3.push(0, g)           # 3 of 3: completes with everyone
        np.testing.assert_allclose(ws[0].pull_sync(0), -3.0 * np.ones(4))
        for w in ws + [w3]:
            w.wait_all()

        # membership broadcast reached the static workers too: their
        # 1/num_workers gradient pre-scale must track the new size
        assert ws[0].num_workers == 3 and ws[1].num_workers == 3

        # next round counts all three as well
        outs = _round(ws + [w3], 0, [g, g, g])
        for o in outs:
            np.testing.assert_allclose(o, -6.0 * np.ones(4))
    finally:
        sim.shutdown()


def test_join_bootstrap_pull_does_not_deadlock():
    """Advisor r4 HIGH: the joiner's natural bootstrap order is pull
    the current model, THEN push.  Join raises the open round's target
    to include the joiner, so under the old serving rule (park any pull
    while count > 0) the joiner's own bootstrap pull parked behind a
    round that only its push could complete — a deadlock that also
    wedged the static workers.  Non-contributor pulls are now served
    from the last completed round, so the bootstrap pull returns
    immediately even with a round open and waiting for the joiner."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        _round(ws, 0, [g, g])                   # store = -2

        # the join lands first (target -> 3), THEN the static workers
        # push: the open round now waits for the joiner's contribution
        w3 = sim.add_worker(0)
        w3.init(0, np.zeros(4, np.float32))
        ws[0].push(0, g)
        ws[1].push(0, g)
        # the bootstrap pull: the open round (2 of 3) can only complete
        # with w3's own push — under the old serving rule this parked
        # forever (and the statics' pulls behind it).  Non-contributors
        # are now served the last completed round's weights.
        pulled = w3.pull_sync(0)                # old rule: hangs forever
        np.testing.assert_allclose(pulled, -2.0 * np.ones(4))

        # the joiner contributes: the waiting round completes for all
        w3.push(0, g)
        outs = [w.pull_sync(0) for w in ws + [w3]]
        for o in outs:
            np.testing.assert_allclose(o, -5.0 * np.ones(4))
        for w in ws + [w3]:
            w.wait_all()
    finally:
        sim.shutdown()


def test_lagging_worker_pull_serves_last_completed_round():
    """A worker one round behind (others already pushed round r+1) asks
    for round r's weights: it must get the store's last-completed value,
    not park behind the open r+1 round (which its own push feeds)."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        _round(ws, 0, [g, g])                   # round r completes: -2
        ws[0].push(0, g)                        # r+1 opens (1 of 2)
        # ws[1] has not contributed to r+1 — its pull gets round r
        np.testing.assert_allclose(ws[1].pull_sync(0), -2.0 * np.ones(4))
        ws[1].push(0, g)                        # r+1 completes: -4
        np.testing.assert_allclose(ws[0].pull_sync(0), -4.0 * np.ones(4))
        for w in ws:
            w.wait_all()
    finally:
        sim.shutdown()


def test_leave_and_push_completion_race_is_single():
    """Advisor r4 MEDIUM: a push deciding completion (outside the lock)
    racing a leave that lowers the target must not run _round_complete
    twice for one key — the second call would crash taking the
    already-None accumulator.  Hammer the interleaving: many rounds
    where the last static push and a leave/rejoin land back to back."""
    import threading

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=3)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.01})
        g = np.ones(64, np.float32)
        for _ in range(10):
            ws[0].push(0, g)
            ws[1].push(0, g)
            # racing pair: the completing third push vs a leave that
            # also sees count >= lowered target
            t_push = threading.Thread(target=ws[2].push, args=(0, g))
            t_leave = threading.Thread(target=ws[2].leave_party)
            t_push.start(); t_leave.start()
            t_push.join(); t_leave.join()
            # both statics can still pull (no crashed server thread)
            out = ws[0].pull_sync(0)
            assert np.isfinite(out).all()
            ws[0].wait_all(); ws[1].wait_all(); ws[2].wait_all()
            # rejoin for the next iteration
            ws[2].join_party()
        srv = sim.local_servers[0]
        assert srv.left_workers == 10 and srv.joined_workers == 10
    finally:
        sim.shutdown()


def test_leave_restores_count_and_releases_stalled_round():
    """Graceful leave: the target drops at the boundary, and a round the
    leaver never reached completes without it instead of stalling."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        w3 = sim.add_worker(0)
        w3.init(0, np.zeros(4, np.float32))

        outs = _round(ws + [w3], 0, [g, g, g])  # 3-way round: -3
        np.testing.assert_allclose(outs[0], -3.0 * np.ones(4))

        # the two static workers push the NEXT round (2 of 3) — it
        # stalls until the third contributor's fate resolves
        ws[0].push(0, g)
        ws[1].push(0, g)
        res = w3.leave_party()
        assert res["num_workers"] == 2
        assert sim.local_servers[0].left_workers == 1
        # the leave released the stalled round at count 2
        np.testing.assert_allclose(ws[0].pull_sync(0), -5.0 * np.ones(4))
        for w in ws:
            w.wait_all()

        # subsequent rounds count 2 again
        outs = _round(ws, 0, [g, g])
        np.testing.assert_allclose(outs[0], -7.0 * np.ones(4))
    finally:
        sim.shutdown()


def test_static_plan_worker_can_leave():
    """The membership registry is seeded with the static plan, so a PLAN
    worker's leave lowers the target too (advisor r4: it used to be
    silently treated as a replayed leave, stalling every later round)."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        _round(ws, 0, [g, g])
        res = ws[1].leave_party()
        assert res["num_workers"] == 1
        # worker 0 trains on alone — rounds complete at count 1
        ws[0].push(0, g)
        np.testing.assert_allclose(ws[0].pull_sync(0), -3.0 * np.ones(4))
        ws[0].wait_all()
    finally:
        sim.shutdown()


def test_join_under_wan_compression():
    """Join interplay with the WAN codec path: a joiner folds into a
    party whose push-ups ride BSC — the pull-direction compressor's
    per-subscriber tracked views and the join are independent, so
    training must continue and the WAN must stay compressed."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        compression="bsc"))
    try:
        ws = sim.all_workers()
        rng = np.random.default_rng(0)
        for w in ws:
            w.init(0, np.zeros(4096, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        ws[0].set_gradient_compression({"type": "bsc", "ratio": 0.05})
        g = rng.standard_normal(4096).astype(np.float32)
        _round(ws, 0, [g, g])
        base = sim.wan_bytes()["wan_send_bytes"]

        w3 = sim.add_worker(0)
        w3.init(0, np.zeros(4096, np.float32))
        outs = _round(ws + [w3], 0, [g, g, g])
        # all three replicas agree post-join
        np.testing.assert_allclose(outs[0], outs[2], rtol=1e-5, atol=1e-6)
        # and the WAN hop stayed sparse (well under the dense 2x16KB
        # push+pull a vanilla round would cost)
        sent = sim.wan_bytes()["wan_send_bytes"] - base
        assert sent < 0.5 * (2 * 4096 * 4), sent
    finally:
        sim.shutdown()


def test_join_survives_drop_injection():
    """ADD_NODE is a control message outside the resender; the client
    RPC retries (and the server handler is idempotent by node id), so a
    join must succeed across a lossy fabric and must not double-count
    when a reply — not the request — was the drop."""
    from geomx_tpu.transport.van import FaultPolicy

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        resend_timeout_ms=100),  # recovers dropped DATA traffic; the
        #                          ADD_NODE rpc has its own retry
        fault=FaultPolicy(drop_rate=0.3, seed=7))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        w3 = sim.add_worker(0)  # retries under 30% drop
        assert w3.num_workers == 3
        srv = sim.local_servers[0]
        # idempotency: however many requests got through, ONE member
        assert srv._workers_target == 3, srv._workers_target
        assert srv.joined_workers >= 1
    finally:
        sim.shutdown()


def test_join_under_intra_ts():
    """VERDICT r4 item 6: join used to be rejected under the intra-party
    TS overlay (fixed member set).  The membership broadcast now updates
    the TsScheduler's dissemination targets and the TsPushScheduler's
    pairing threshold, so a joiner both receives overlay relays and
    participates in the merge tree."""
    import threading

    import jax

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        enable_intra_ts=True))
    try:
        x, y = synthetic_classification(n=256, shape=(8, 8, 1), seed=0)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        hist = {}

        def train(kv, widx, nw, steps):
            it = ShardedIterator(x, y, 16, widx, nw, seed=1)
            hist[widx] = run_worker(kv, params, grad_fn, it, steps,
                                    barrier_init=False)

        ths = [threading.Thread(target=train, args=(w, i, 2, 2))
               for i, w in enumerate(ws)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert len(hist) == 2, "static TS round hung"

        w3 = sim.add_worker(0)
        # scheduler member sets tracked the join.  The membership
        # broadcast is asynchronous — join_party() returning only means
        # the SERVER folded the joiner in, not that every scheduler's
        # hook has run yet — so poll with a short deadline instead of
        # asserting immediately (advisor r5: flaky under load)
        import time as _time

        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            if all(str(w3.po.node) in sched.members
                   for sched in sim.ts_schedulers):
                break
            _time.sleep(0.02)
        for sched in sim.ts_schedulers:
            assert str(w3.po.node) in sched.members
        ths = [threading.Thread(target=train, args=(w, i, 3, 2))
               for i, w in enumerate(ws + [w3])]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert len(hist) == 3, "post-join TS round hung"
        assert len(hist[2]) == 2  # the joiner trained full rounds
        assert np.isfinite([h[0] for h in hist[2]]).all()
    finally:
        sim.shutdown()


def test_join_under_hfa_renormalizes_weight_mean():
    """VERDICT r4 item 6: join under HFA.  Workers push weight/n; a
    transition round mixes denominators (statics at old n, joiner at
    new n) and a leave can complete a round short — either way the
    accumulated Σ w_i/n_i is renormalized by Σ 1/n_i (announced per
    push as hfa_n), so the party 'mean' stays a convex combination and
    the weights are never scale-inflated."""
    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        use_hfa=True, hfa_k2=1))
    try:
        ws = sim.all_workers()
        w_val = 6.0 * np.ones(4, np.float32)
        for w in ws:
            w.init(0, w_val.copy())
        # HFA round at n=2: both push w/2 with hfa_n=2 -> mean = 6
        for w in ws:
            w.push(0, w_val / 2, body={"hfa_n": 2})
        np.testing.assert_allclose(ws[0].pull_sync(0), w_val)
        for w in ws:
            w.wait_all()

        w3 = sim.add_worker(0)
        w3.init(0, w_val.copy())
        assert w3.num_workers == 3
        # transition round: statics still at n=2 (stale pre-scale),
        # joiner at n=3.  Unnormalized sum = 6/2+6/2+6/3 = 8 (a 1.33x
        # weight inflation); renormalized by S = 1/2+1/2+1/3 -> 6.
        ws[0].push(0, w_val / 2, body={"hfa_n": 2})
        ws[1].push(0, w_val / 2, body={"hfa_n": 2})
        w3.push(0, w_val / 3, body={"hfa_n": 3})
        np.testing.assert_allclose(ws[0].pull_sync(0), w_val, rtol=1e-6)
        for w in ws + [w3]:
            w.wait_all()

        # leave completes a round short: 2 of 3 pushed, leaver exits.
        # Σ w/3 * 2 = 4 would SHRINK the weights; renormalized -> 6.
        ws[0].push(0, w_val / 3, body={"hfa_n": 3})
        ws[1].push(0, w_val / 3, body={"hfa_n": 3})
        w3.leave_party()
        np.testing.assert_allclose(ws[0].pull_sync(0), w_val, rtol=1e-6)
        for w in ws:
            w.wait_all()
    finally:
        sim.shutdown()


def test_party_leave_lowers_global_tier_target():
    """VERDICT r4 item 6: graceful PARTY leave.  The global tier's
    aggregation target (num_global_workers) drops at the round
    boundary; a round the leaving party never reached completes with
    the remaining parties instead of stalling forever.  (The
    reference's global membership is static; recovery is a TODO at
    van.cc:224 — this goes beyond it.)"""
    sim = Simulation(Config(
        topology=Topology(num_parties=3, workers_per_party=1)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        # global tier applies the PARTY mean: -lr * (3g)/3 = -1
        outs = _round(ws, 0, [g, g, g])
        np.testing.assert_allclose(outs[0], -1.0 * np.ones(4))

        # parties 0 and 1 push the next round; party 2 leaves instead
        ws[0].push(0, g)
        ws[1].push(0, g)
        res = sim.local_servers[2].leave_global()
        for gs_reply in res.values():
            assert gs_reply["num_global_workers"] == 2
        # the stalled round completes with two parties: -(2g)/2 = -1
        np.testing.assert_allclose(ws[0].pull_sync(0), -2.0 * np.ones(4))
        ws[0].wait_all(); ws[1].wait_all()

        # subsequent rounds count 2 parties
        outs = _round(ws[:2], 0, [g, g])
        np.testing.assert_allclose(outs[0], -3.0 * np.ones(4))

        # replayed leave is idempotent
        res = sim.local_servers[2].leave_global()
        for gs_reply in res.values():
            assert gs_reply["num_global_workers"] == 2
    finally:
        sim.shutdown()


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["plain", "tsengine", "hfa"])
def test_worker_joins_over_real_tcp(mode):
    """Process-level join (the reference's ADD_NODE is inherently
    multi-process, van.cc:41-112): a full TCP topology trains while an
    out-of-plan worker process registers via --join --advertise, trains
    a couple of rounds, and leaves gracefully; everyone exits 0 and the
    server's exit stats show the join+leave.  Parametrized over the
    plain loop, the TS overlay (peers/scheduler must learn the joiner's
    out-of-plan ADDRESS from the membership broadcast — relays and ask
    replies dial it) and HFA (weight-mean renormalization)."""
    import os
    import re
    import subprocess
    import sys
    import time

    from tests.test_tcp import free_base_port

    flags = {"plain": [], "tsengine": ["--tsengine"], "hfa": ["--hfa"]}[mode]
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    topo = Topology(num_parties=1, workers_per_party=2)
    base = free_base_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu")

    def spawn(role, extra):
        return subprocess.Popen(
            [sys.executable, "-m", "geomx_tpu.launch", "--role", role,
             "--parties", "1", "--workers", "2",
             "--base-port", str(base)] + extra + flags,
            cwd=cwd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    procs = {str(n): spawn(str(n), ["--steps", "8"])
             for n in topo.all_nodes()}
    # the joiner: out-of-plan rank 2, binds past the plan's ports.
    # Launched immediately — it registers while the static workers are
    # still in jax compile, and runs fewer steps than they do so its
    # rounds are a prefix of theirs (leave covers the rest)
    join_role = "worker:2@p0"
    procs[join_role] = spawn(join_role, [
        "--steps", "2", "--join",
        "--advertise", f"127.0.0.1:{base + 40}"])
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        for r, p in procs.items():
            assert p.returncode == 0, \
                f"{r} rc={p.returncode}: {outputs[r][-1000:]}"
        assert "joined as rank 2" in outputs[join_role], outputs[join_role]
        assert "left cleanly" in outputs[join_role], outputs[join_role]
        srv_out = outputs["server:0@p0"]
        m = re.search(r"joined=(\d+) left=(\d+)", srv_out)
        assert m and m.group(1) == "1" and m.group(2) == "1", srv_out
        for w in ("worker:0@p0", "worker:1@p0"):
            assert "steps=8" in outputs[w], outputs[w][-500:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()


def test_joined_worker_trains_a_model():
    """End-to-end: CNN training continues across a join and the loss
    keeps improving with three contributors."""
    import jax

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import flatten_params, run_worker

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        x, y = synthetic_classification(n=256, shape=(8, 8, 1), seed=0)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})

        import threading

        hist = {}

        def train(kv, widx, nw, steps):
            it = ShardedIterator(x, y, 16, widx, nw)
            hist[widx] = run_worker(kv, params, grad_fn, it, steps,
                                    barrier_init=False)

        ths = [threading.Thread(target=train, args=(w, i, 2, 3))
               for i, w in enumerate(ws)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

        w3 = sim.add_worker(0)
        ths = [threading.Thread(target=train, args=(w, i, 3, 3))
               for i, w in enumerate(ws + [w3])]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(hist[2]) == 3  # the joiner trained full rounds
        losses = [h[0] for h in hist[0]]
        assert np.isfinite(losses).all()
    finally:
        sim.shutdown()


def test_party_leave_under_hfa():
    """Party leave while the global tier is in HFA mode: accumulated
    milestone DELTAS must complete additively (not through the
    optimizer) when the leave lowers the target, and the surviving
    party trains on."""
    sim = Simulation(Config(
        topology=Topology(num_parties=2, workers_per_party=1),
        use_hfa=True, hfa_k2=1))
    try:
        ws = sim.all_workers()
        w_val = 4.0 * np.ones(4, np.float32)
        for w in ws:
            w.init(0, w_val.copy())
        # one full HFA round: both parties push mean weights -> both
        # replicas equal the cross-party mean (still 4.0)
        for w in ws:
            w.push(0, w_val, body={"hfa_n": 1})
        for w in ws:
            np.testing.assert_allclose(w.pull_sync(0), w_val)
            w.wait_all()

        # party 0 pushes the next round; party 1 leaves instead of
        # pushing — the round must complete additively with party 0's
        # milestone delta alone
        ws[0].push(0, 6.0 * np.ones(4, np.float32), body={"hfa_n": 1})
        res = sim.local_servers[1].leave_global()
        for gs_reply in res.values():
            assert gs_reply["num_global_workers"] == 1
        out = ws[0].pull_sync(0)
        assert np.isfinite(out).all()
        ws[0].wait_all()

        # the surviving party keeps syncing rounds cleanly
        ws[0].push(0, 5.0 * np.ones(4, np.float32), body={"hfa_n": 1})
        out2 = ws[0].pull_sync(0)
        assert np.isfinite(out2).all()
        ws[0].wait_all()
    finally:
        sim.shutdown()


def _join_trains_under(cfg_kwargs, loop="plain"):
    """Shared driver: 2 static workers train, a third joins, everyone
    trains again; returns the joiner's history."""
    import threading

    import jax

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker, run_worker_esync

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2),
        **cfg_kwargs))
    try:
        x, y = synthetic_classification(n=256, shape=(8, 8, 1), seed=0)
        _, params, grad_fn = create_cnn_state(
            jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
        ws = sim.all_workers()
        if loop == "plain":
            ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        hist = {}

        def train(kv, widx, nw, n):
            # ShardedIterator samples with replacement and never ends —
            # no cycling wrapper needed (esync draws rounds x local
            # steps batches from it)
            it = ShardedIterator(x, y, 16, widx, nw, seed=1)
            if loop == "esync":
                hist[widx] = run_worker_esync(
                    kv, params, grad_fn, it, n, barrier_init=False,
                    max_local_steps=4)
            else:
                hist[widx] = run_worker(kv, params, grad_fn, it, n,
                                        barrier_init=False)

        ths = [threading.Thread(target=train, args=(w, i, 2, 2))
               for i, w in enumerate(ws)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert len(hist) == 2, "static phase hung"
        w3 = sim.add_worker(0)
        ths = [threading.Thread(target=train, args=(w, i, 3, 2))
               for i, w in enumerate(ws + [w3])]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=150)
        assert len(hist) == 3, "post-join phase hung"
        assert np.isfinite([h[0] for h in hist[2]]).all()
        return hist[2]
    finally:
        sim.shutdown()


def test_join_under_p3():
    """Join under P3 (sliced piggybacked push_pull): the joiner's
    sliced keys fold into the same per-key round machinery — membership
    is uniform across scheduling modes, like the reference's ADD_NODE."""
    _join_trains_under(dict(enable_p3=True, p3_slice_elems=5_000))


def test_join_under_esync():
    """Join under ESync: the state server's plan is report-keyed (no
    fixed member set), the HFA weight mean renormalizes via hfa_n —
    a joiner simply starts reporting and training."""
    _join_trains_under(dict(use_hfa=True), loop="esync")


def test_concurrent_joins_get_unique_ranks():
    """Two workers joining the same party simultaneously must receive
    DISTINCT ranks and both be counted — rank assignment and the target
    bump live under the server lock, but the test pins the end-to-end
    guarantee (the reference's scheduler serializes ADD_NODE the same
    way, van.cc:41-112)."""
    import threading

    sim = Simulation(Config(
        topology=Topology(num_parties=1, workers_per_party=2)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(4, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        g = np.ones(4, np.float32)
        _round(ws, 0, [g, g])

        joined = {}

        def join_one(slot):
            joined[slot] = sim.add_worker(0)

        ths = [threading.Thread(target=join_one, args=(i,))
               for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert len(joined) == 2, "a join hung"
        srv = sim.local_servers[0]
        assert srv._workers_target == 4
        ranks = sorted(srv._members.values())
        assert ranks == [0, 1, 2, 3], ranks  # unique, gapless

        # all four train a round together
        all_ws = ws + list(joined.values())
        for w in joined.values():
            w.init(0, np.zeros(4, np.float32))
            assert np.isfinite(w.pull_sync(0)).all()
        outs = _round(all_ws, 0, [g] * 4)
        for o in outs:
            np.testing.assert_allclose(o, outs[0])
    finally:
        sim.shutdown()


def test_party_leave_prunes_dcasgd_backups():
    """MixedSync + DCASGD keeps a previous-weight snapshot per SENDER
    (party server); a party's graceful leave must drop its snapshots or
    full-model copies stay pinned in global-server RAM for the run."""
    sim = Simulation(Config(
        topology=Topology(num_parties=2, workers_per_party=1),
        sync_global_mode=False))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(8, np.float32))
        ws[0].set_optimizer({"type": "dcasgd", "lr": 0.1})
        g = np.ones(8, np.float32)
        for w in ws:
            w.push(0, g)
            w.pull_sync(0)
            w.wait_all()
        gs = sim.global_servers[0]
        senders = set()
        for st in gs.optimizer.state.values():
            senders |= set(st.get("prev", {}))
        assert len(senders) == 2, senders  # both party servers tracked

        res = sim.local_servers[1].leave_global()
        for reply in res.values():
            assert reply["num_global_workers"] == 1
        leaver = str(sim.local_servers[1].po.node)
        for st in gs.optimizer.state.values():
            assert leaver not in st.get("prev", {})
        # survivor keeps training
        ws[0].push(0, g)
        assert np.isfinite(ws[0].pull_sync(0)).all()
        ws[0].wait_all()
    finally:
        sim.shutdown()
