"""Crash-tolerant membership (PR 2 tentpole): heartbeat-driven worker
eviction, barrier release to the survivor set, zombie push fencing +
rejoin, and local-server crash recovery (party fold → warm boot →
unfold → worker replay).

tests/test_failover.py covers the global tier (PR 1); this file covers
the two lower HiPS tiers, whose recovery the reference leaves as a TODO
(ref: van.cc:224).  Fast tests are tier-1 (in-proc fabric, thread-level
kills via ``Van.kill``); the e2e crash soak with loss-parity against an
uninterrupted control run is marked slow.
"""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Group, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.utils.metrics import system_snapshot

pytestmark = pytest.mark.chaos


def _cfg(parties=1, workers=2, **kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("heartbeat_timeout_s", 0.4)
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=workers), **kw)


def _wait_for(pred, timeout=20.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _delta(base, snap, key):
    """System counters are process-global; tests assert DELTAS so any
    earlier heartbeat/chaos test in the same pytest process can't bleed
    into these assertions."""
    return snap.get(key, 0) - base.get(key, 0)


def test_worker_eviction_unblocks_rounds_barriers_and_fences_zombie():
    """The whole worker-tier story in one deployment: a worker dies
    without a leave; the scheduler's detector synthesizes the forced
    leave (stalled round completes on the survivor), releases the FSA
    barrier already waiting on the corpse, fences the zombie's late
    push behind its recorded boot incarnation, and the rejoin door
    hands out a fresh rank that restores the full aggregation count."""
    sim = Simulation(_cfg())
    base = system_snapshot()
    try:
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(8, np.float32))
        w0.set_optimizer({"type": "sgd", "lr": 1.0})
        for w in (w0, w1):
            w.push(0, np.ones(8, np.float32))
        # grads are not pre-scaled here: sum=2, 1 global worker → -2/round
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -2 * np.ones(8, np.float32))
        for w in (w0, w1):
            w.wait_all()

        sim.kill_worker(0, 1)  # no leave message — just silence
        # a barrier entered while the corpse is still a member must
        # release when the eviction recomputes membership, not time out
        released = []

        def barrier():
            t0 = time.monotonic()
            w0.po.barrier(Group.WORKERS, timeout=30)
            released.append(time.monotonic() - t0)

        th = threading.Thread(target=barrier)
        th.start()
        # the survivor's round stalls at count 1/2 until the eviction
        # lowers the target — then it completes without the dead worker
        w0.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -3 * np.ones(8, np.float32))
        th.join(30)
        assert released and released[0] < 20, "barrier not released"
        assert _wait_for(lambda: sim.eviction_monitors[0].evictions == 1)
        ls = sim.local_servers[0]
        assert ls.evicted_workers == 1

        # zombie: the SAME incarnation resumes pushing — fenced with an
        # error telling it to rejoin, counts stay uncorrupted
        w1.po.start()
        w1.push(0, np.ones(8, np.float32))
        with pytest.raises(RuntimeError, match="evicted"):
            w1.wait_all()
        assert ls.eviction_fenced_pushes >= 1

        # the dynamic-join door lifts the fence with a FRESH rank...
        info = w1.join_party()
        assert info["rank"] == 2 and info["num_workers"] == 2
        # ...and the rejoined worker contributes to full rounds again
        for w in (w0, w1):
            w.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -5 * np.ones(8, np.float32))

        # eviction/fence counters are in the system-metrics registry
        snap = system_snapshot()
        assert _delta(base, snap, "scheduler:0@p0.worker_evictions") == 1
        assert _delta(base, snap, "server:0@p0.evicted_workers") == 1
        assert _delta(base, snap,
                      "server:0@p0.eviction_fenced_pushes") >= 1
    finally:
        sim.shutdown()


def test_eviction_disabled_leaves_membership_alone():
    """``enable_eviction=False``: the dead-node table still observes,
    but nothing actuates — no monitors, no fold, no fence."""
    sim = Simulation(_cfg(enable_eviction=False))
    try:
        assert sim.eviction_monitors == []
        assert sim.recovery_monitor is None
        w0, _ = sim.all_workers()
        sim.kill_worker(0, 1)
        assert _wait_for(lambda: w0.num_dead_nodes() >= 1, 10)
        assert sim.local_servers[0].evicted_workers == 0
    finally:
        sim.shutdown()


def test_barrier_timeout_names_dead_and_missing_members():
    """Satellite: a barrier timeout must be diagnosable from the
    exception alone — it names the scheduler's dead list and the
    members that never entered."""
    sim = Simulation(_cfg(enable_eviction=False))  # stall must persist
    try:
        w0, _ = sim.all_workers()
        sim.kill_worker(0, 1)
        # let the heartbeat table notice the corpse first
        sched = sim.offices["scheduler:0@p0"]
        assert _wait_for(lambda: "worker:1@p0" in sched.dead_nodes(), 10)
        with pytest.raises(TimeoutError) as ei:
            w0.po.barrier(Group.WORKERS, timeout=1.0)
        msg = str(ei.value)
        assert "worker:1@p0" in msg, msg
        assert "never entered" in msg, msg
        assert "dead-node list" in msg, msg
    finally:
        sim.shutdown()


def test_num_dead_nodes_degrades_on_scheduler_timeout():
    """Satellite: a slow/dead scheduler must not propagate TimeoutError
    out of num_dead_nodes — log and serve the last-known count."""
    sim = Simulation(_cfg(workers=2, enable_eviction=False))
    try:
        w0, _ = sim.all_workers()
        assert w0.num_dead_nodes() == 0
        sim.kill_worker(0, 1)
        assert _wait_for(lambda: w0.num_dead_nodes() >= 1, 10)
        last = w0.num_dead_nodes()
        # now the scheduler itself goes dark: the query times out but
        # the call degrades to the last-known count instead of raising
        sim.offices["scheduler:0@p0"].van.kill()
        sim.offices["scheduler:0@p0"].stop()
        assert w0.num_dead_nodes(timeout=0.3) == last
    finally:
        sim.shutdown()


def test_local_server_crash_folds_party_and_warm_boot_recovers():
    """The tentpole's third leg: a dead local server folds its party out
    of global rounds (the WAN root keeps making progress), a replacement
    warm-boots the model state from the global tier, the party folds
    back in, and the party's workers retarget/replay and contribute
    again — with every step visible in the system-metrics registry."""
    sim = Simulation(_cfg(parties=2, workers=1, request_retry_s=0.5,
                          heartbeat_timeout_s=0.5))
    base = system_snapshot()
    try:
        w0, w1 = sim.all_workers()
        for w in (w0, w1):
            w.init(0, np.zeros(8, np.float32))
        w0.set_optimizer({"type": "sgd", "lr": 1.0})
        for w in (w0, w1):
            w.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -np.ones(8, np.float32))
        for w in (w0, w1):
            w.wait_all()

        sim.kill_local_server(1)
        # party 0's round stalls at 1/2 contributors until the monitor
        # folds party 1 out — then the WAN root completes it
        w0.push(0, np.ones(8, np.float32))
        np.testing.assert_allclose(w0.pull_sync(0),
                                   -2 * np.ones(8, np.float32))
        assert _wait_for(lambda: sim.recovery_monitor.party_folds == 1)
        assert sim.global_servers[0].party_folds == 1

        # a REPLACEMENT process: fresh postoffice, empty store
        ls2 = sim.restart_local_server(1)
        assert ls2.store == {}
        assert _wait_for(lambda: sim.recovery_monitor.party_unfolds == 1,
                         30), "party never folded back in"
        # warm boot adopted the global tier's current weights
        assert ls2.warm_boots == 1
        np.testing.assert_allclose(ls2.store[0],
                                   -2 * np.ones(8, np.float32))
        assert _wait_for(lambda: w1.server_recoveries >= 1, 10)

        # both parties train again and agree (FSA invariant restored)
        for w in (w0, w1):
            w.push(0, np.ones(8, np.float32))
        a, b = w0.pull_sync(0), w1.pull_sync(0)
        np.testing.assert_allclose(a, -3 * np.ones(8, np.float32))
        np.testing.assert_allclose(a, b)

        snap = system_snapshot()
        assert _delta(base, snap, "global_scheduler:0.party_folds") == 1
        assert _delta(base, snap, "global_scheduler:0.party_unfolds") == 1
        assert _delta(base, snap, "global_server:0.party_folds") == 1
        assert _delta(base, snap, "global_server:0.party_unfolds") == 1
        assert _delta(base, snap, "server:0@p1.warm_boots") == 1
        assert _delta(base, snap,
                      "worker:0@p1.server_recoveries") >= 1
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# slow e2e acceptance: SIGKILL-equivalent kills mid-TRAINING with loss
# parity against an uninterrupted control run
# ---------------------------------------------------------------------------


def _train_cnn(workers, hist, errs, num_all=None,
               barrier_init=False, progress=None):
    """``progress[widx]`` counts completed steps live (log_fn), so a
    caller can kill a node provably MID-training."""
    import jax

    from geomx_tpu.data import ShardedIterator, synthetic_classification
    from geomx_tpu.models import create_cnn_state
    from geomx_tpu.training import run_worker

    x, y = synthetic_classification(n=512, shape=(8, 8, 1), seed=3)
    _, params, grad_fn = create_cnn_state(
        jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
    n = num_all or len(workers)

    def train(kv, widx, nsteps):
        def tick(step, _loss, _acc):
            if progress is not None:
                progress[widx] = step + 1

        try:
            it = ShardedIterator(x, y, 16, widx, n, seed=4)
            hist[widx] = run_worker(kv, params, grad_fn, it, nsteps,
                                    barrier_init=barrier_init,
                                    log_fn=tick)
        except Exception as e:  # noqa: BLE001 — asserted by callers
            errs.append((widx, repr(e)))

    ths = [threading.Thread(target=train, args=(kv, i, s))
           for i, (kv, s) in enumerate(workers)]
    for t in ths:
        t.start()
    return ths


@pytest.mark.slow
def test_crash_eviction_e2e_worker_and_local_server():
    """Acceptance (ISSUE 2): SIGKILL-equivalent kill of one worker and
    (separately) one local server mid-training with heartbeats enabled.
    No round or barrier stalls past the detection timeout: training
    completes on the survivor set with loss parity versus an
    uninterrupted control run, the restarted local server rejoins and
    contributes again, and the eviction / fence / party-fold counters
    are visible in the system-metrics registry.

    Phase timings ride the distributed tracer (PhaseTracer) and are
    dumped as a Chrome-trace artifact at the end — a future flake of
    this soak names the phase (and the eviction/fold control instants
    around it) that stalled."""
    from geomx_tpu.trace import PhaseTracer

    pt = PhaseTracer("crash_eviction_e2e")
    steps = 24
    kill_after = 8

    # ---- control: same topology, nobody killed -------------------------
    pt.begin("control_run")
    sim = Simulation(Config(topology=Topology(num_parties=2,
                                              workers_per_party=2)))
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        hist, errs = {}, []
        ths = _train_cnn([(w, steps) for w in ws], hist, errs)
        for t in ths:
            t.join(300)
        assert not errs, errs
        control_loss = float(np.mean([hist[i][-1][0] for i in hist]))
    finally:
        sim.shutdown()

    # ---- phase A: a worker dies ungracefully mid-training --------------
    pt.begin("worker_crash_eviction")
    sim = Simulation(Config(
        topology=Topology(num_parties=2, workers_per_party=2),
        heartbeat_interval_s=0.1, heartbeat_timeout_s=0.8,
        request_retry_s=1.0))
    base = system_snapshot()
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        hist, errs = {}, []
        # the victim (party 0, rank 1) runs only kill_after steps, then
        # goes silent WITHOUT a leave; survivors run the full count and
        # stall at round kill_after+1 until the eviction folds it out
        jobs = [(w, kill_after if i == 1 else steps)
                for i, w in enumerate(ws)]
        ths = _train_cnn(jobs, hist, errs)
        ths[1].join(300)
        assert 1 in hist, errs
        pt.mark("kill_worker", node="worker:1@p0")
        sim.kill_worker(0, 1)
        for t in ths:
            t.join(300)
        assert not errs, errs
        assert len(hist) == 4, "a survivor hung after the worker kill"
        crash_loss = float(np.mean(
            [hist[i][-1][0] for i in hist if i != 1]))
        assert np.isfinite(crash_loss)
        assert abs(crash_loss - control_loss) < 0.5, (crash_loss,
                                                      control_loss)
        assert sim.eviction_monitors[0].evictions == 1
        # the zombie's late push is fenced — counts stay uncorrupted
        ws[1].po.start()
        ws[1].push(0, np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="evicted"):
            ws[1].wait_all()
        snap = system_snapshot()
        assert _delta(base, snap, "scheduler:0@p0.worker_evictions") == 1
        assert _delta(base, snap,
                      "server:0@p0.eviction_fenced_pushes") >= 1
    finally:
        sim.shutdown()

    # ---- phase B: a local server dies mid-training, replacement rejoins
    pt.begin("local_server_crash_recovery")
    sim = Simulation(Config(
        topology=Topology(num_parties=2, workers_per_party=1),
        heartbeat_interval_s=0.1, heartbeat_timeout_s=0.8,
        request_retry_s=1.0))
    base = system_snapshot()
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "adam", "lr": 0.01})
        hist, errs, progress = {}, [], {}
        ths = _train_cnn([(w, steps) for w in ws], hist, errs,
                         progress=progress)
        # let a few rounds land, then kill party 1's server MID-training;
        # its worker blocks on replayed requests until the warm boot
        assert _wait_for(lambda: progress.get(1, 0) >= 6, 120), progress
        pt.mark("kill_local_server", party=1)
        sim.kill_local_server(1)
        time.sleep(2.5)  # detection + fold; party 0 keeps training
        killed_at = progress.get(1, 0)
        assert killed_at < steps, "server outlived the training run"
        pt.mark("restart_local_server", party=1)
        sim.restart_local_server(1)
        # the warm-booted replacement folds the party back in
        assert _wait_for(
            lambda: sim.recovery_monitor.party_unfolds == 1, 60), \
            "party never folded back in"
        # FSA tail under skewed step counts: party 0 advanced solo while
        # party 1 was folded out, so its worker finishes first and stops
        # pushing — it must withdraw from the global tier gracefully
        # (leave_global) or the recovered party's catch-up rounds would
        # wait on it forever
        ths[0].join(300)
        assert 0 in hist, errs
        sim.local_servers[0].leave_global()
        for t in ths:
            t.join(300)
        assert not errs, errs
        # BOTH workers finish all steps — the folded party's worker
        # resumed through retarget+replay after the warm boot
        assert len(hist) == 2, "a worker hung across the server crash"
        for h in hist.values():
            assert len(h) == steps
            assert np.isfinite([loss for loss, _ in h]).all()
        server_loss = float(np.mean([hist[i][-1][0] for i in hist]))
        assert abs(server_loss - control_loss) < 0.5, (server_loss,
                                                       control_loss)
        assert sim.recovery_monitor.party_folds == 1
        snap = system_snapshot()
        assert _delta(base, snap, "global_scheduler:0.party_folds") == 1
        assert _delta(base, snap,
                      "global_scheduler:0.party_unfolds") == 1
        assert _delta(base, snap, "server:0@p1.warm_boots") >= 1
        assert _delta(base, snap,
                      "worker:0@p1.server_recoveries") >= 1
        # the replacement server ended the run hosting the full model
        ls2 = sim.local_servers[1]
        assert ls2.store and all(
            np.isfinite(v).all() for v in ls2.store.values())
    finally:
        sim.shutdown()
        print("phase timeline artifact:", pt.dump(), flush=True)
