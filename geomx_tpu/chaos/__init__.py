"""Scripted chaos drivers (beyond the reference, whose fault story is
manual process kills).  ``churn`` turns the PR 2 crash tooling + the
graceful preemption drain into a seeded, repeatable spot-churn engine
for elasticity soaks (docs/deployment.md "Elasticity & preemption")."""

from geomx_tpu.chaos.churn import (ChurnOrchestrator, ChurnPhase,
                                   ChurnPlan)
from geomx_tpu.chaos.netfault import (NetFaultOrchestrator,
                                      NetFaultPhase, NetFaultPlan,
                                      install_env_netfaults)

__all__ = ["ChurnOrchestrator", "ChurnPhase", "ChurnPlan",
           "NetFaultOrchestrator", "NetFaultPhase", "NetFaultPlan",
           "install_env_netfaults"]
