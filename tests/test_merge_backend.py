"""Pluggable merge backend (ISSUE 10): numpy stays the bit-identical
default, the jax backend merges identically on CPU (f32 exact for
integer-valued gradients, the same arrival-order fold), the donation /
promotion / deterministic contracts hold, and the whole kvstore round
machinery runs green with the lanes forced onto jax
(``scripts/run_backend_smoke.sh`` runs the broader sweep).

Runs on the virtual 8-device CPU mesh (conftest), so the mesh psum
party-aggregation path and the opt-in quantized rung are exercised for
real — one pre-reduced buffer per device, reduced by ``shard_map`` +
``psum`` at round close."""

import os
import tracemalloc

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.backend import (NumpyBackend, make_merge_backend,
                                       resolve_merge_backend)
from geomx_tpu.kvstore.common import make_merge_lanes, resolve_server_shards


def _jax_backend(**cfg_kw):
    from geomx_tpu.kvstore.jax_backend import JaxBackend

    return JaxBackend(Config(topology=Topology(), **cfg_kw))


# ---- selection rules ---------------------------------------------------------

def test_auto_resolves_numpy_on_cpu_host(monkeypatch):
    # the suite pins JAX_PLATFORMS=cpu (conftest): auto must pick the
    # host reference path without so much as importing jax.  Clear the
    # env fallback — run_backend_smoke.sh runs this very test under
    # GEOMX_MERGE_BACKEND=jax
    monkeypatch.delenv("GEOMX_MERGE_BACKEND", raising=False)
    cfg = Config(topology=Topology())
    assert cfg.merge_backend == "auto"
    assert resolve_merge_backend(cfg) == "numpy"
    assert isinstance(make_merge_backend(cfg), NumpyBackend)


def test_deterministic_forces_numpy():
    cfg = Config(topology=Topology(), merge_backend="jax",
                 deterministic=True)
    assert resolve_merge_backend(cfg) == "numpy"


def test_env_fallback_shakes_directly_constructed_configs(monkeypatch):
    monkeypatch.setenv("GEOMX_MERGE_BACKEND", "jax")
    cfg = Config(topology=Topology())  # merge_backend left at "auto"
    assert resolve_merge_backend(cfg) == "jax"
    # an explicit field wins over the env fallback
    assert resolve_merge_backend(
        Config(topology=Topology(), merge_backend="numpy")) == "numpy"


def test_unknown_backend_rejected():
    with pytest.raises(ValueError, match="merge_backend"):
        resolve_merge_backend(Config(topology=Topology(),
                                     merge_backend="cuda"))


def test_jax_backend_caps_lanes():
    cfg = Config(topology=Topology(), server_shards=8)
    be = _jax_backend(server_shards=8)
    mu, shards = make_merge_lanes(cfg, "test", be)
    try:
        assert mu.n == shards.n == min(resolve_server_shards(cfg),
                                       be.max_lanes)
    finally:
        shards.stop()


# ---- merge contracts ---------------------------------------------------------

def test_donated_adopt_no_hidden_copy_on_numpy_path():
    """The zero-copy recv view flows straight into the accumulator: a
    donated writeable f32 buffer IS adopted (same object), and the seed
    allocates nothing of the payload's size — the tracemalloc guard
    that keeps a 200 MB push from silently costing 400 MB."""
    be = NumpyBackend(Config(topology=Topology()))
    v = np.ones(1 << 20, np.float32)  # 4 MB
    tracemalloc.start()
    try:
        acc = be.seed(v, donated=True)
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    assert acc is v, "donated buffer must be adopted, not copied"
    assert peak < v.nbytes // 2, f"hidden copy on the adopt path: {peak}"
    # the defensive half of the contract: non-donated payloads are
    # copied (the sender may still be aliasing the buffer)
    assert be.seed(v, donated=False) is not v
    frozen = np.ones(16, np.float32)
    frozen.flags.writeable = False
    adopted = be.seed(frozen, donated=True)
    assert adopted is not frozen and adopted.flags.writeable


def test_donated_adopt_honored_by_jax_backend():
    """The jax path's adopt contract is the single staged H2D copy:
    h2d_bytes counts exactly one staging of every payload, and the wire
    buffer is never aliased by live round state (mutating it after the
    push must not change the merge)."""
    be = _jax_backend()
    v1 = np.ones(1024, np.float32)
    v2 = np.full(1024, 2.0, np.float32)
    acc = be.seed(v1, donated=True)
    acc = be.accumulate(acc, v2)
    v1[:] = 99.0  # the donated buffer is ours again after staging
    v2[:] = 99.0
    out = be.materialize(acc)
    np.testing.assert_array_equal(out, np.full(1024, 3.0, np.float32))
    assert be.stats()["h2d_bytes"] == v1.nbytes + v2.nbytes
    assert be.stats()["merge_device_ms"] > 0


def test_f16_promotion_rule_pinned_across_backends():
    """A float16 push promotes to a float32 accumulator on the FIRST
    touch, and both backends produce bit-identical f32 — the dtype
    promotion half of the MergeBackend contract."""
    rng = np.random.default_rng(7)
    v16 = rng.standard_normal(4096).astype(np.float16)
    w16 = rng.standard_normal(4096).astype(np.float16)
    outs = {}
    for name, be in (("numpy", NumpyBackend(Config(topology=Topology()))),
                     ("jax", _jax_backend())):
        acc = be.seed(v16.copy(), donated=True)
        acc = be.accumulate(acc, w16.copy())
        out = be.materialize(acc)
        assert out.dtype == np.float32
        outs[name] = out.tobytes()
    assert outs["numpy"] == outs["jax"]


def test_f32_merge_exact_parity_numpy_vs_jax():
    """Integer-valued f32 gradients make float accumulation exact in
    any order, so the two backends must agree BIT-identically — the
    CPU parity bar the bench child re-checks at 20M elements."""
    rng = np.random.default_rng(3)
    pushes = [rng.integers(-64, 64, 8192).astype(np.float32)
              for _ in range(8)]
    results = {}
    for name, be in (("numpy", NumpyBackend(Config(topology=Topology()))),
                     ("jax", _jax_backend())):
        acc = be.seed(pushes[0].copy(), donated=True)
        for p in pushes[1:]:
            acc = be.accumulate(acc, p.copy())
        results[name] = be.materialize(acc).tobytes()
    assert results["numpy"] == results["jax"]


def test_mesh_psum_party_aggregation(monkeypatch):
    """With the 8-device mesh and a big tensor the jax backend parks
    one pre-reduced part per device slot and the round close reduces
    across them as one shard_map+psum collective — same exact sum."""
    import geomx_tpu.kvstore.jax_backend as jb

    monkeypatch.setattr(jb, "_MESH_MIN_ELEMS", 1024)
    be = _jax_backend()
    if len(be._devices) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    pushes = [np.full(4096, float(i + 1), np.float32) for i in range(5)]
    acc = be.seed(pushes[0], donated=True)
    for p in pushes[1:]:
        acc = be.accumulate(acc, p)
    assert acc.spread and len(acc.parts) > 1, "mesh path not engaged"
    out = be.materialize(acc)
    np.testing.assert_array_equal(out, np.full(4096, 15.0, np.float32))


def test_quantized_rung_error_bounded(monkeypatch):
    """The opt-in EQuARX rung routes the mesh collective through the
    int8 block-quantized psum: the party sum is recovered within the
    documented per-element bound (each element quantized at most twice
    per leg at <= blockmax/127)."""
    import geomx_tpu.kvstore.jax_backend as jb

    monkeypatch.setattr(jb, "_MESH_MIN_ELEMS", 1024)
    be = _jax_backend(merge_quantized=True)
    if len(be._devices) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(11)
    pushes = [rng.standard_normal(4096).astype(np.float32)
              for _ in range(4)]
    acc = be.seed(pushes[0], donated=True)
    for p in pushes[1:]:
        acc = be.accumulate(acc, p)
    out = be.materialize(acc)
    exact = np.sum(pushes, axis=0)
    k = len(pushes)
    bound = 2.0 * k * max(np.abs(p).max() for p in pushes) / 127.0
    assert np.max(np.abs(out - exact)) <= bound
    assert be.stats()["merge_quantized"] is True


# ---- e2e: the kvstore round machinery on the jax lanes -----------------------

def _train_rounds(steps=2, lr=0.1, **cfg_kw):
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=2),
                 **cfg_kw)
    sim = Simulation(cfg)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(2048, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": lr})
        for _ in range(steps):
            for i, w in enumerate(ws):
                w.push(0, np.full(2048, float(i + 1), np.float32))
            for w in ws:
                w.pull_sync(0)
                w.wait_all()
        out = ws[0].pull_sync(0)
        ls = sim.local_servers[0]
        return np.array(out), ls._backend.name, ls.stats()
    finally:
        sim.shutdown()


def test_e2e_jax_backend_matches_numpy_bitwise():
    """The acceptance bar: a full two-tier FSA round trip under
    GEOMX_MERGE_BACKEND=jax lands bit-identical weights to the numpy
    default (integer-valued grads — exact under any fold order), and
    the servers actually ran the jax lanes (stats say so, with the
    device counters moving)."""
    w_np, be_np, _ = _train_rounds(merge_backend="numpy")
    w_jx, be_jx, st = _train_rounds(merge_backend="jax")
    assert (be_np, be_jx) == ("numpy", "jax")
    assert st["merge_backend"] == "jax"
    assert st["h2d_bytes"] > 0
    assert w_np.tobytes() == w_jx.tobytes()


def test_jax_backend_registry_gauges_set():
    from geomx_tpu.utils.metrics import system_snapshot

    _, _, st = _train_rounds(merge_backend="jax", steps=1)
    snap = system_snapshot()
    keyed = {k for k in snap if k.endswith(".merge_device_ms")
             or k.endswith(".h2d_bytes")}
    assert keyed, f"merge gauges missing from the registry: {sorted(snap)[:8]}"


def test_deterministic_suite_unaffected():
    """deterministic + jax request = numpy lanes, single stripe — the
    replayable debug mode cannot be put on a device dispatch order."""
    w_a, be_a, _ = _train_rounds(merge_backend="jax", deterministic=True)
    w_b, be_b, _ = _train_rounds(merge_backend="numpy", deterministic=True)
    assert be_a == be_b == "numpy"
    assert w_a.tobytes() == w_b.tobytes()
