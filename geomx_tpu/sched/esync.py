"""ESync: straggler-balancing local-step assignment (state server).

The reference lists ESync as designed-but-not-integrated
(ref: README.md:45 "To be integrated", paper README.md:111 — ESync,
IEEE TSC'20): a synchronous algorithm for heterogeneous clusters where a
**state server** orchestrates how many LOCAL optimizer steps each worker
runs between synchronizations, so fast workers do useful extra work
instead of idling at the barrier and every worker reaches the server at
roughly the same wall-clock time.

This build integrates it natively: the state server is a small planner
hosted by each party's LocalServer (ESync is intra-domain — across data
centers the usual HiPS/HFA tiers apply unchanged), reachable over the
command channel (``Ctrl.ESYNC``).  The sync itself rides the HFA
machinery: workers push mean weights every round; only the number of
local steps per round varies per worker.

Planner model: a worker's reach-server time for ``M`` local steps is
``R_i(M) = M * step_i + comm_i`` (measured per-local-step compute time
and per-round push+pull time, EWMA-smoothed).  The target is the slowest
worker running ``min_steps``::

    T = max_i (min_steps * step_i + comm_i)
    M_i = clamp(floor((T - comm_i) / step_i), min_steps, max_steps)

so the slowest worker gets ``min_steps`` and faster workers fill the
same wall-clock window with more local progress.
"""

from __future__ import annotations

import threading
from typing import Dict


class EsyncState:
    """The state server's planner.  Thread-safe; one per party."""

    def __init__(self, min_steps: int = 1, max_steps: int = 64,
                 smooth: float = 0.5, clip: float = 4.0):
        assert 1 <= min_steps <= max_steps
        self.min_steps = int(min_steps)
        self.max_steps = int(max_steps)
        self.smooth = float(smooth)  # EWMA weight of the NEW sample
        # outlier clamp: a new sample may move at most ``clip``x away
        # from the worker's running estimate before entering the EWMA.
        # One GC-pause/paging spike (easily 100x) then shifts the
        # estimate by at most (1 + smooth*(clip-1)) and heals next
        # round, while a GENUINE slowdown still converges geometrically
        # (each round the estimate may grow clip-fold).  The party
        # target is a max over these estimates, so without the clamp a
        # single worker's single bad round would stretch every sibling's
        # assignment (VERDICT r2 weak #6).
        self.clip = float(clip)
        self._mu = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}

    def report(self, worker: str, step_s: float, comm_s: float,
               max_steps: int = 0) -> None:
        """Record one round's measurements (seconds per LOCAL step, and
        transmission time for the round).  ``max_steps`` > 0 records
        THIS worker's assignment cap (workers may size their data
        pipelines differently; a single shared cap would let one
        worker's larger cap override another's)."""
        step_s = max(float(step_s), 1e-9)
        comm_s = max(float(comm_s), 0.0)
        with self._mu:
            st = self._stats.get(worker)
            if st is None:
                st = self._stats[worker] = {"step_s": step_s,
                                            "comm_s": comm_s,
                                            "cap": self.max_steps}
            else:
                a, c = self.smooth, self.clip
                # upward-only clamp: the threat is a transient SLOW round
                # inflating the party target; downward corrections are
                # legitimate and common (first-round jit compile, cache
                # warmup) and only affect the reporting worker's own
                # assignment, so they pass through unclamped
                step_s = min(step_s, max(st["step_s"], 1e-3) * c)
                comm_s = min(comm_s, max(st["comm_s"], 1e-3) * c)
                st["step_s"] += a * (step_s - st["step_s"])
                st["comm_s"] += a * (comm_s - st["comm_s"])
            if max_steps > 0:
                st["cap"] = min(self.max_steps, int(max_steps))

    def plan(self) -> Dict[str, int]:
        """Per-worker local step counts balancing reach-server time."""
        with self._mu:
            if not self._stats:
                return {}
            target = max(self.min_steps * st["step_s"] + st["comm_s"]
                         for st in self._stats.values())
            out = {}
            for w, st in self._stats.items():
                m = int((target - st["comm_s"]) / st["step_s"])
                out[w] = max(self.min_steps, min(st["cap"], m))
            return out

    def drop(self, worker: str) -> bool:
        """Forget a departed worker (membership fold / eviction /
        graceful leave).  Without this, the departed worker's stale
        ``step_s`` estimate stays in the ``max`` reach-time target
        forever — a slow worker that left would permanently inflate
        every survivor's assignment.  A joiner needs no inverse: it is
        seeded at ``min_steps`` until its first report.  Returns True
        when the worker had stats to forget."""
        with self._mu:
            return self._stats.pop(worker, None) is not None

    def workers(self):
        """Currently-tracked worker names (planner hygiene tests)."""
        with self._mu:
            return sorted(self._stats)

    def steps_for(self, worker: str) -> int:
        """Assignment for one worker (min_steps until it has reported)."""
        return self.plan().get(worker, self.min_steps)
