"""Pallas flash-style block attention with online-softmax stats.

The ring-attention hot loop (``parallel/ring_attention.py``) computes one
(Q-block, KV-block) partial attention per ICI hop.  Its einsum form
materializes the [B, Tq, H, Tk] score/prob tensors in HBM between the
two matmuls on every hop — O(B·H·Tq·Tk) traffic that grows quadratically
with the per-device sequence.  This kernel fuses QK^T → mask → online
softmax → PV inside VMEM, so HBM traffic drops to the O(B·H·T·D) tensor
reads/writes, with both matmuls on the MXU in the input dtype
(bf16-friendly) and float32 accumulation.

Semantics are IDENTICAL to ``ring_attention._block_attn`` with its
``bias_for`` causal bias (fully-masked rows produce m = -1e30 and junk
l/o that the ring's merge wipes via beta → 0 — same contract), so the
kernel drops into the ring as ``fast="flash"`` with no change to the
merge.  The backward pass recomputes the block through the einsum
reference and takes its exact VJP (standard flash remat trade: no
stored probs, ~1 extra block forward in bwd).

Positions arrive as runtime offsets (scalar-prefetch): ``q_off``/
``k_off`` are the global indices of the blocks' first tokens, so ONE
kernel serves every ring hop — diagonal (causal triangle), below-
diagonal (fully visible) and above-diagonal (fully masked) — without
data-dependent control flow.

Correctness coverage runs on CPU via pallas TPU interpret mode
(tests/test_block_attention.py); on-chip the lane dim wants head_dim a
multiple of 128 (the flagship's is 128).

Ref for the role this plays: the reference's fused 16:1 packing kernels
(gradient_compression-inl.h:40-139) are its example of hot-loop kernel
discipline; this is ours for the SP attention path (no reference
counterpart — GeoMX has no attention at all).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _block_attn_ref(q, k, v, offs, causal: bool):
    """Einsum reference (bit-compatible with ring_attention._block_attn
    fast mode + bias_for): the primal definition the VJP differentiates."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = jnp.float32(1.0 / np.sqrt(D))
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = offs[0] + jnp.arange(Tq)
        k_pos = offs[1] + jnp.arange(Tk)
        vis = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(vis[None, :, None, :], s, _NEG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bqhk,bkhd->bqhd", p.astype(q.dtype), v,
                   preferred_element_type=jnp.float32)
    return m, l, o.astype(jnp.float32)


def _kernel(offs_ref, q_ref, k_ref, v_ref, m_ref, l_ref, o_ref, *,
            scale: float, causal: bool, bq: int, Tk: int):
    iq = pl.program_id(2)
    q = q_ref[0, :, 0, :]    # [bq, D]
    kk = k_ref[0, :, 0, :]   # [Tk, D]
    vv = v_ref[0, :, 0, :]
    s = lax.dot_general(q, kk, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    if causal:
        q_pos = (offs_ref[0] + iq * bq
                 + lax.broadcasted_iota(jnp.int32, (bq, Tk), 0))
        k_pos = offs_ref[1] + lax.broadcasted_iota(jnp.int32, (bq, Tk), 1)
        s = jnp.where(q_pos >= k_pos, s, jnp.float32(_NEG))
    m = jnp.max(s, axis=1)
    p = jnp.exp(s - m[:, None])
    l = jnp.sum(p, axis=1)
    o = lax.dot_general(p.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    m_ref[0, :, 0] = m
    l_ref[0, :, 0] = l
    o_ref[0, :, 0, :] = o


def _pick_bq(Tq: int) -> int:
    """Q-block rows per grid step.  Default ladder prefers the largest
    tile that divides Tq; ``GEOMX_FLASH_BLOCK_Q`` (set from the on-chip
    autotune child, bench.py --child flash_autotune) overrides when it
    divides Tq — tile choice is a pure performance knob, semantics are
    offset-driven and identical for every bq."""
    import os

    override = os.environ.get("GEOMX_FLASH_BLOCK_Q")
    if override:
        try:
            bq = int(override)
        except ValueError:
            bq = 0  # malformed value: fall through to the ladder —
            #         never kill a training step over a perf knob
        if 0 < bq <= Tq and Tq % bq == 0:
            return bq
    for cand in (256, 128, 64, 32, 16, 8):
        if Tq % cand == 0:
            return min(cand, Tq)
    return Tq


def _flash_fwd_impl(q, k, v, offs, causal: bool):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq = _pick_bq(Tq)
    grid = (B, H, Tq // bq)
    kernel = functools.partial(
        _kernel, scale=1.0 / np.sqrt(D), causal=causal, bq=bq, Tk=Tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        # index_map gets the scalar-prefetch ref appended to grid indices
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, offs: (b, i, h, 0)),
            pl.BlockSpec((1, Tk, 1, D), lambda b, h, i, offs: (b, 0, h, 0)),
            pl.BlockSpec((1, Tk, 1, D), lambda b, h, i, offs: (b, 0, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1), lambda b, h, i, offs: (b, i, h)),
            pl.BlockSpec((1, bq, 1), lambda b, h, i, offs: (b, i, h)),
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, offs: (b, i, h, 0)),
        ],
    )
    m, l, o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Tq, H), jnp.float32),
            jax.ShapeDtypeStruct((B, Tq, H), jnp.float32),
            jax.ShapeDtypeStruct((B, Tq, H, D), jnp.float32),
        ],
    )(offs, q, k, v)
    return m, l, o


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def flash_block_attention(q, k, v, offs, causal: bool = True):
    """One fused (Q-block, KV-block) partial attention.

    ``q`` [B, Tq, H, D]; ``k``/``v`` [B, Tk, H, D]; ``offs`` int32 [2] =
    (global index of q's first token, global index of k's first token).
    Returns ``(m [B,Tq,H], l [B,Tq,H], o [B,Tq,H,D])`` float32 — the
    unnormalized online-softmax partials ring_attention merges.
    """
    return _flash_fwd_impl(q, k, v, offs, causal)


def _vjp_fwd(q, k, v, offs, causal: bool):
    return _flash_fwd_impl(q, k, v, offs, causal), (q, k, v, offs)


def _vjp_bwd(causal: bool, res, cots):
    q, k, v, offs = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _block_attn_ref(q_, k_, v_, offs, causal),
        q, k, v)
    dq, dk, dv = vjp(cots)
    return dq, dk, dv, None


flash_block_attention.defvjp(_vjp_fwd, _vjp_bwd)
