#!/usr/bin/env bash
# Event-driven transport smoke lane (ISSUE 12 satellite): run the
# transport + kvstore/failover/eviction/sharded-global parity subset
# under the reactor fabric (selector loops, write queues, timer wheel)
# and the lightweight-party dispatch path.  Since ISSUE 20 the reactor
# IS the process default (resolve_transport), so this lane inherits it
# — GEOMX_TRANSPORT=reactor is still pinned below so the lane keeps its
# meaning even if someone exports the threads escape hatch in their
# shell.  In-proc Simulations flip into lightweight mode under this
# knob; TcpFabric tests exercise the real non-blocking wire path.
#
# Env: PYTEST_ARGS (extra pytest flags), GEOMX_REACTOR_LOOPS (loop pool
# size, default auto = min(4, cpus)), GEOMX_REACTOR_WORKERS (handler
# pool).  The 128-party soak is separate: pytest -m scale.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu

# lint gate first (ISSUE 14): the reactor-blocking checker statically
# proves no blocking primitive is reachable from SerialChannel
# handlers / selector callbacks / timer ticks — exactly the wedge class
# this parity lane exists to catch dynamically.  A lint finding fails
# the lane before any test runs.
python -m geomx_tpu.analysis

export GEOMX_TRANSPORT=reactor

exec python -m pytest -q -m 'not slow' -p no:cacheprovider \
  tests/test_reactor.py tests/test_transport.py tests/test_tcp.py \
  tests/test_wire_v2.py tests/test_ps.py tests/test_kvstore.py \
  tests/test_failover.py tests/test_eviction.py tests/test_churn.py \
  tests/test_sharded_global.py tests/test_recovery.py \
  tests/test_serve.py tests/test_serve_plane.py \
  ${PYTEST_ARGS:-}
