"""Synthetic datasets + sharded iteration.

The reference trains on MNIST fetched at runtime (ref: examples/cnn.py:49
mx.test_utils.get_mnist); this environment has no egress, so the stand-in
is a class-template image dataset with additive noise — learnable by the
same CNN in a few steps, which is all the acceptance tests need
(correctness oracle = "accuracy/loss curve matches vanilla", SURVEY.md §4).

``ShardedIterator`` reproduces the reference's per-worker data sharding
(ref: examples/cnn.py:49 splits by num_all_workers/worker rank).
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def synthetic_classification(
    n: int = 2048,
    shape: Tuple[int, ...] = (28, 28, 1),
    num_classes: int = 10,
    noise: float = 0.35,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Images = class template + gaussian noise; labels = class id."""
    rng = np.random.default_rng(seed)
    templates = rng.standard_normal((num_classes, *shape)).astype(np.float32)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = templates[y] + noise * rng.standard_normal((n, *shape)).astype(np.float32)
    return x, y


def synthetic_lm(
    n: int = 2048,
    seq: int = 64,
    vocab: int = 256,
    order: float = 0.85,
    seed: int = 0,
) -> np.ndarray:
    """Token sequences from a low-entropy affine chain: with probability
    ``order`` the next token is ``(5*cur + 17) % vocab``, else uniform —
    an LM can cut its loss well below ``log(vocab)`` within a few steps,
    which is all the e2e acceptance needs (same oracle philosophy as
    ``synthetic_classification``).  Returns int32 [n, seq]."""
    rng = np.random.default_rng(seed)
    toks = np.empty((n, seq), np.int32)
    toks[:, 0] = rng.integers(0, vocab, size=n)
    for t in range(1, seq):
        det = (5 * toks[:, t - 1] + 17) % vocab
        rand = rng.integers(0, vocab, size=n)
        toks[:, t] = np.where(rng.random(n) < order, det, rand)
    return toks


class TokenIterator:
    """Sharded batch iterator over token sequences; yields ``(tokens,
    tokens)`` pairs so the generic worker loops (which expect (x, y))
    work unchanged — the LM objective ignores y."""

    def __init__(self, tokens: np.ndarray, batch_size: int,
                 worker_index: int = 0, num_workers: int = 1, seed: int = 0):
        self.tokens = tokens[worker_index::num_workers]
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed + worker_index)

    def __iter__(self):
        return self

    def __next__(self):
        idx = self._rng.integers(0, len(self.tokens), size=self.batch_size)
        batch = self.tokens[idx]
        return batch, batch


class ShardedIterator:
    """Round-robin shard of a dataset for one worker among
    ``num_all_workers`` (global worker index orders shards)."""

    def __init__(self, x: np.ndarray, y: np.ndarray, batch_size: int,
                 worker_index: int = 0, num_workers: int = 1, seed: int = 0):
        self.x = x[worker_index::num_workers]
        self.y = y[worker_index::num_workers]
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed + worker_index)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self):
        idx = self._rng.integers(0, len(self.x), size=self.batch_size)
        return self.x[idx], self.y[idx]
