"""DGT: Differential Gradient Transmission.

Reimplements the reference's DGT data plane (ref: kv_app.h:841-995,
van.cc:707-824, message.h:237-251): a large dense push is chunked into
``block_size``-element blocks; each chunk's *contribution* (EWMA of its
mean |gradient|, α = DGT_CONTRIBUTION_ALPHA) ranks it; the top ``k``
fraction rides the reliable channel 0, the rest spread over N lossy
priority channels.  The receiver reassembles on the reliable final chunk
(which always travels channel 0, ref: kv_app.h:989-991) and fills chunks
lost on the lossy channels with zeros — loss-tolerant best-effort for the
unimportant mass.

Transport mapping: the reference uses raw UDP sockets with DSCP marks;
in-proc the lossy channels are fabric channels with a configurable drop
rate, and on real DCN they map to secondary QUIC/UDP streams.  Modes
(ref: ENABLE_DGT∈{1,2,3}, van.cc:750-824): 1 = lossy channels; 2 = all
chunks reliable (chunking + prioritization only); 3 = all reliable but
unimportant chunks re-quantized to 4-bit (per-chunk min/max scale, two
nibbles per byte — the reference's encode/decode 4-bit path,
van.cc:750-824), trading precision of the low-contribution mass for
8x less wire on it.

Sparse payloads (bsc) are never chunked — dropping a chunk of a
[values ‖ indices] payload would corrupt it; DGT applies to dense and
fp16 pushes like the reference (MergeMsg/MergeMsg_HALF, van.cc:290-328).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomx_tpu.core.config import Config
from geomx_tpu.transport.message import Message


def quant4(vals: np.ndarray):
    """4-bit linear quantization: returns (packed uint8 [(n+1)//2],
    lo, hi).  Two nibbles per byte, low nibble first."""
    v = vals.astype(np.float32)
    lo = float(v.min())
    hi = float(v.max())
    scale = (hi - lo) or 1.0
    q = np.clip(np.round((v - lo) / scale * 15.0), 0, 15).astype(np.uint8)
    if len(q) % 2:
        q = np.append(q, np.uint8(0))
    return (q[0::2] | (q[1::2] << 4)).astype(np.uint8), lo, hi


def dequant4(packed: np.ndarray, n: int, lo: float, hi: float) -> np.ndarray:
    q = np.empty(len(packed) * 2, dtype=np.uint8)
    q[0::2] = packed & 15
    q[1::2] = packed >> 4
    return (q[:n].astype(np.float32) / 15.0 * ((hi - lo) or 1.0)
            + lo).astype(np.float32)


class DgtSender:
    """Chunk + rank + assign channels.  One instance per sending endpoint
    (holds the per-chunk contribution EWMA state)."""

    def __init__(self, config: Config):
        self.block_size = config.dgt_block_size
        self.k = config.dgt_k
        self.k_min = config.dgt_k_min
        self.adaptive = config.dgt_adaptive_k
        self.k_anneal_steps = config.dgt_k_anneal_steps
        self.channels = max(1, config.dgt_udp_channels)
        self.alpha = config.dgt_contrib_alpha
        self.mode = config.enable_dgt
        self._contrib: Dict[Tuple[int, int], float] = {}
        self._steps = 0
        self.dgt4_chunks = 0  # mode-3 observable: 4-bit requant count

    def current_k(self) -> float:
        """Adaptive k decays from k to k_min over training
        (ref: ADAPTIVE_K_FLAG; the reference anneals with iteration).
        The horizon is ``dgt_k_anneal_steps`` (GEOMX_DGT_K_ANNEAL_STEPS)."""
        if not self.adaptive:
            return self.k
        t = min(1.0, self._steps / max(1, self.k_anneal_steps))
        return self.k + (self.k_min - self.k) * t

    def split(self, msg: Message) -> List[Message]:
        """Split one data message into chunk messages. The final chunk
        (seq == seq_end) carries the full meta (keys/lens/body) and always
        rides channel 0 so completion always triggers."""
        vals = msg.vals
        assert vals is not None and vals.dtype in (np.float32, np.float16)
        self._steps += 1
        n = len(vals)
        bs = self.block_size
        nchunks = (n + bs - 1) // bs
        first_key = int(msg.keys[0]) if msg.keys is not None and len(msg.keys) else -1

        # contribution EWMA per (first_key, chunk index)
        contribs = []
        for c in range(nchunks):
            blk = vals[c * bs:(c + 1) * bs]
            mean_mag = float(np.mean(np.abs(blk.astype(np.float32))))
            key = (first_key, c)
            old = self._contrib.get(key)
            ewma = mean_mag if old is None else (
                self.alpha * mean_mag + (1 - self.alpha) * old)
            self._contrib[key] = ewma
            contribs.append(ewma)

        order = np.argsort(-np.asarray(contribs), kind="stable")
        k_cnt = max(1, int(np.ceil(self.current_k() * nchunks)))
        channel_of = {}
        for rank, c in enumerate(order):
            if self.mode != 1 or rank < k_cnt:
                channel_of[int(c)] = 0
            else:
                channel_of[int(c)] = 1 + (rank - k_cnt) % self.channels

        rank_of = {int(c): r for r, c in enumerate(order)}
        out = []
        for c in range(nchunks):
            blk = vals[c * bs:(c + 1) * bs]
            # mode 3: requantize unimportant (non-final) chunks to 4-bit
            chunk_body = None
            # (dtype already constrained to f32/f16 by the entry assert)
            if (self.mode == 3 and rank_of[c] >= k_cnt
                    and c != nchunks - 1):
                packed, lo, hi = quant4(blk)
                chunk_body = {"_dgt4": {"n": len(blk), "lo": lo, "hi": hi}}
                blk = packed
                self.dgt4_chunks += 1
            chunk = Message(
                sender=msg.sender, recipient=msg.recipient, domain=msg.domain,
                app_id=msg.app_id, customer_id=msg.customer_id,
                timestamp=msg.timestamp, request=msg.request, push=msg.push,
                pull=msg.pull, cmd=msg.cmd, priority=msg.priority,
                compr=msg.compr, vals=blk,
                first_key=first_key, seq=c, seq_begin=0, seq_end=nchunks - 1,
                channel=channel_of[c],
                total_bytes=n,            # total elements of the payload
                val_bytes=c * bs,         # element offset of this chunk
                # every chunk carries the logical message's trace context
                # — reassembly must restore it whichever chunks survive
                # the lossy channels, and a lost lossy chunk must not
                # orphan the round's causal chain
                trace_id=msg.trace_id, span_id=msg.span_id,
                parent_span_id=msg.parent_span_id, sampled=msg.sampled,
                # every chunk carries the WAN-policy epoch too: the
                # reassembled push must fence like an unsplit one
                policy_epoch=msg.policy_epoch,
                # ...and the sender incarnation nonce (the van re-stamps
                # it at send time, but the field table must be complete:
                # reassembly restores boot from the completion chunk and
                # replay dedup keys on it)
                boot=msg.boot,
            )
            if chunk_body is not None:
                chunk.body = chunk_body
            if c == nchunks - 1:
                # meta rides the completion chunk, always reliable; it also
                # lists the reliable seqs so the receiver can wait for any
                # channel-0 chunk lost to generic drop injection (they are
                # retransmitted by the resender; lossy chunks are not)
                chunk.keys = msg.keys
                chunk.lens = msg.lens
                chunk.channel = 0
                channel_of[c] = 0
                chunk.body = {
                    "_dgt_reliable": [int(s) for s, ch in channel_of.items()
                                      if ch == 0],
                    "orig": msg.body,
                }
            out.append(chunk)
        # send lossy/low-rank chunks first, completion chunk last
        out.sort(key=lambda m: (m.seq == m.seq_end, -m.channel))
        return out


class DgtReassembler:
    """Receiver side: merge chunks; finalize on the completion chunk,
    zero-filling chunks lost on the lossy channels
    (ref: ProcessDataMsg msg_map merge, van.cc:330-370)."""

    def __init__(self):
        import collections

        self._buf: Dict[tuple, dict] = {}
        self._mu = threading.Lock()
        self.dgt4_decoded = 0  # mode-3 observable: 4-bit chunks decoded
        # finalized-round tombstones: stragglers (late retransmits of
        # reliable chunks) must not recreate buffer entries
        self._done = set()
        self._done_order = collections.deque()
        self._done_cap = 10_000

    @staticmethod
    def _key(msg: Message) -> tuple:
        return (str(msg.sender), msg.app_id, msg.customer_id,
                msg.timestamp, msg.first_key)

    def accept(self, msg: Message) -> Optional[Message]:
        """Returns the reassembled logical message when complete."""
        key = self._key(msg)
        with self._mu:
            if key in self._done:
                return None  # straggler retransmit of a finalized round
            ent = self._buf.setdefault(key, {"chunks": {}, "final": None})
            ent["chunks"][msg.seq] = msg
            if msg.seq == msg.seq_end:
                ent["final"] = msg
            final = ent["final"]
            if final is None:
                return None
            have = ent["chunks"]
            # wait for every RELIABLE chunk (channel 0): those are either
            # in-order before the final chunk or retransmitted by the
            # resender; chunks lost on lossy channels are gone forever and
            # get zero-filled
            reliable = (final.body or {}).get("_dgt_reliable", [])
            if any(s not in have for s in reliable):
                return None
            del self._buf[key]
            self._done.add(key)
            self._done_order.append(key)
            if len(self._done_order) > self._done_cap:
                self._done.discard(self._done_order.popleft())
        total = max(0, int(final.total_bytes))
        vals = np.zeros(total, dtype=final.vals.dtype)
        for s, chunk in have.items():
            # defensive bounds: a chunk that decoded despite in-flight
            # damage (legacy unstamped frames) may carry a nonsense
            # offset/length — scatter it nowhere (≡ a lost lossy chunk,
            # zero-filled) instead of raising out of the receive path
            try:
                off = int(chunk.val_bytes)
                meta4 = (chunk.body or {}).get("_dgt4") if isinstance(
                    chunk.body, dict) else None
                if meta4 is not None:
                    dec = dequant4(chunk.vals, int(meta4["n"]),
                                   meta4["lo"], meta4["hi"])
                else:
                    dec = chunk.vals
                n = len(dec)
                if off < 0 or off + n > total:
                    continue
                vals[off:off + n] = dec
                if meta4 is not None:
                    self.dgt4_decoded += 1
            except (ValueError, TypeError, KeyError, OverflowError):
                continue
        out = Message(
            sender=final.sender, recipient=final.recipient,
            domain=final.domain, app_id=final.app_id,
            customer_id=final.customer_id, timestamp=final.timestamp,
            request=final.request, push=final.push, pull=final.pull,
            cmd=final.cmd, priority=final.priority, compr=final.compr,
            keys=final.keys, vals=vals, lens=final.lens,
            body=(final.body or {}).get("orig"),
            # the reassembled logical message IS the original on the
            # timeline: same trace/span ids (any surviving chunk carries
            # them; the completion chunk always does)
            trace_id=final.trace_id, span_id=final.span_id,
            parent_span_id=final.parent_span_id, sampled=final.sampled,
            policy_epoch=final.policy_epoch,
            # restore the sender incarnation nonce: RecentRequests keys
            # replay dedup on (sender, boot, ts) — a reassembled push
            # with boot=0 would collide with a replaced predecessor's
            # requests after an ADDR_UPDATE recovery
            boot=final.boot,
            # the reassembly buffer is freshly allocated and exclusively
            # ours — the receiving server may adopt it as its accumulator
            donated=True,
        )
        return out
