"""Robustness under combined faults: loss + latency + resend, full
training flow (the reference's PS_DROP_MSG + PS_RESEND acceptance style,
ref: SURVEY.md §4 fault injection)."""

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.transport.van import FaultPolicy


@pytest.mark.slow
def test_training_survives_lossy_latent_network():
    """20% drop on every link + 2ms LAN / 10ms WAN latency + resend:
    training must complete with exact FSA semantics."""
    cfg = Config(
        topology=Topology(num_parties=2, workers_per_party=2),
        resend_timeout_ms=50,
    )
    fault = FaultPolicy(drop_rate=0.2, latency_s=0.002, wan_latency_s=0.01,
                        seed=13)
    sim = Simulation(cfg, fault=fault)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(512, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for step in range(5):
            for w in ws:
                w.push(0, np.ones(512, np.float32))
            outs = [w.pull_sync(0) for w in ws]
        # party sum 2, global mean 2 → -0.2/step × 5
        for out in outs:
            np.testing.assert_allclose(out, -1.0, rtol=1e-5)
        assert sim.fabric.dropped > 0  # the network really was lossy
    finally:
        sim.shutdown()


@pytest.mark.slow
def test_compressed_training_survives_loss():
    """BSC compression + drops + resend still converges identically on
    both replicas (codec state must not desync under retransmits)."""
    cfg = Config(
        topology=Topology(num_parties=2, workers_per_party=1),
        resend_timeout_ms=50,
    )
    sim = Simulation(cfg, fault=FaultPolicy(drop_rate=0.15, seed=7))
    try:
        ws = sim.all_workers()
        for p in range(2):
            sim.worker(p, 0).set_gradient_compression(
                {"type": "bsc", "ratio": 0.1})
        for w in ws:
            w.init(0, np.zeros(2000, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        rng = np.random.default_rng(0)
        for step in range(4):
            g = np.abs(rng.standard_normal(2000)).astype(np.float32)
            for w in ws:
                w.push(0, g)
            outs = [w.pull_sync(0) for w in ws]
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
        assert outs[0].mean() < -0.005
    finally:
        sim.shutdown()


@pytest.mark.slow
def test_scale_4x4_multigps_bsc_with_midrun_recovery(tmp_path):
    """VERDICT r1 item 5 — the scale ceiling test: 4 parties x 4 workers,
    3 global servers, a 50M-element tensor sharded by MultiGPS, BSC on,
    and a global-server kill+restart mid-run (checkpoint + request
    replay).  Asserts convergence direction + cross-party FSA sync."""
    import time

    from geomx_tpu.core.config import NodeId
    from geomx_tpu.kvstore.server import GlobalServer
    from geomx_tpu.ps import Postoffice

    N = 50_000_000  # 200 MB float32; partitions across the 3 global shards
    cfg = Config(
        topology=Topology(num_parties=4, workers_per_party=4,
                          num_global_servers=3),
        request_retry_s=2.0,
        checkpoint_dir=str(tmp_path),
        auto_ckpt_updates=1,
    )
    sim = Simulation(cfg)
    try:
        ws = sim.all_workers()
        init = np.zeros(N, np.float32)
        for w in ws:
            w.init(0, init)
        del init
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for p in range(4):
            sim.worker(p, 0).set_gradient_compression(
                {"type": "bsc", "ratio": 0.001})

        rng = np.random.default_rng(0)
        # one shared grad buffer: in-proc pushes are zero-copy, so 16
        # workers sharing it keeps peak memory bounded
        g = np.abs(rng.standard_normal(N)).astype(np.float32)

        t0 = time.perf_counter()
        rounds = 3
        for r in range(rounds):
            for w in ws:
                w.push(0, g)
            if r == 1:
                # kill global shard 0 mid-round: pushes are in flight,
                # its parked round is lost with it.  The restart resumes
                # from the auto-checkpoint; the local servers' replay
                # (request_retry_s) re-sends the unanswered pushes
                gs0 = sim.global_servers[0]
                node = gs0.po.node
                gs0.stop()
                gs0.po.stop()
                new_po = Postoffice(node, cfg.topology, sim.fabric, cfg)
                new_gs = GlobalServer(new_po, cfg)
                # checkpoint BEFORE the van starts: otherwise replayed
                # pushes race the empty store (the launch.py ordering)
                new_gs.load_checkpoint(
                    f"{tmp_path}/global_server_{node.rank}.npz")
                new_po.start()
                sim.global_servers[0] = new_gs
                sim.offices[str(node)] = new_po
            # one puller per party bounds peak memory (4 x 200 MB)
            outs = [sim.worker(p, 0).pull_sync(0) for p in range(4)]
            for w in ws:
                w.wait_all()
        dt = time.perf_counter() - t0

        # every party identical (FSA through MultiGPS shards + recovery)
        for p in range(1, 4):
            np.testing.assert_allclose(outs[p][:100_000], outs[0][:100_000],
                                       atol=1e-6)
        # BSC top-k applied SOME negative update to the largest entries
        assert outs[0].min() < -1e-4
        # observability: per-server merged bytes/s (16 pushes x 200 MB x
        # rounds over 4 local servers)
        merged_gb = 16 * (N * 4 / 1e9) * rounds / 4
        print(f"stress: {dt:.1f}s for {rounds} rounds; "
              f"~{merged_gb / dt:.2f} GB/s merged per local server")
    finally:
        sim.shutdown()
