"""Shared mechanism for the reference-parity example wrappers: each
cnn_<feature>.py preset-injects its flags and delegates to cnn.main
(user-supplied flags still win — argparse takes the last occurrence)."""

import sys
from pathlib import Path


def run(flags: str) -> int:
    sys.argv[1:1] = flags.split()
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from cnn import main

    return main()
