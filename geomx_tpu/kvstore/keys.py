"""Tensor → ps-key encoding.

Mirrors the semantics of the reference key encoders
(ref: src/kvstore/kvstore_dist.h:721-799):

- ``EncodeDefaultKey``: tensors smaller than ``bigarray_bound`` live whole
  on one server chosen by hash ``(tensor_id * 9973) % num_shards``; bigger
  tensors are partitioned evenly across **all** shards (this is also what
  MultiGPS does at the global tier, ref: kvstore_dist_server.h:1770-1810).
- ``EncodeP3Key``: slice every ``slice_elems`` elements into its own key so
  each slice can be scheduled/prioritized independently
  (ref: kvstore_dist.h:763-799).

One encoding is used for both tiers: the shard count is the number of
*global* servers, so the same ps keys flow worker → local server → global
server, and the local server (which owns the whole key space at tier 1)
can push each key straight to its owning global shard.

ps-key layout: ``shard * step + tensor_id * CHUNK_SPACE + chunk_idx`` where
``step = MAX_KEY // num_shards``.

The shard count is config-driven: ``Config.global_shards`` /
``GEOMX_GLOBAL_SHARDS`` / ``launch.py --global-shards`` set
``Topology.num_global_servers``, and the assignment here is a pure
deterministic function of (tensor_id, size, num_shards) — every node
computes the identical plan with no coordination.  The range → SERVER
binding is the dynamic half: ``split_range`` (ps/postoffice.py) maps
range k to global server rank k at plan time, and per-shard failover /
live key-range reassignment move a range's CURRENT holder at runtime
(kvstore/replication.py) without touching the key encoding.
"""

from __future__ import annotations

import dataclasses
from typing import List

from geomx_tpu.ps.postoffice import MAX_KEY

CHUNK_SPACE = 1 << 20  # max chunks of one tensor per shard


@dataclasses.dataclass(frozen=True)
class KeyPart:
    """One wire key of an encoded tensor."""

    ps_key: int
    start: int   # element offset into the flat tensor
    length: int  # element count
    shard: int   # owning (global-)server rank
    priority: int = 0


def encode_tensor(
    tensor_id: int,
    size: int,
    num_shards: int,
    bigarray_bound: int = 1_000_000,
    slice_elems: int = 0,
    base_priority: int = 0,
) -> List[KeyPart]:
    """Compute the wire keys for one tensor.

    ``slice_elems > 0`` selects P3-style slicing; otherwise default
    hashing/partitioning. Returned parts are ordered by ``start``.
    """
    assert size > 0
    step = MAX_KEY // num_shards
    parts: List[KeyPart] = []
    if slice_elems > 0:
        nchunks = (size + slice_elems - 1) // slice_elems
        for c in range(nchunks):
            shard = c % num_shards
            idx = c // num_shards
            start = c * slice_elems
            parts.append(KeyPart(
                ps_key=shard * step + tensor_id * CHUNK_SPACE + idx,
                start=start,
                length=min(slice_elems, size - start),
                shard=shard,
                priority=base_priority,
            ))
    elif size >= bigarray_bound and num_shards > 1:
        # even partition across all shards (ref: kvstore_dist.h:743-756)
        per = size // num_shards
        for s in range(num_shards):
            start = s * per
            length = (size - start) if s == num_shards - 1 else per
            parts.append(KeyPart(
                ps_key=s * step + tensor_id * CHUNK_SPACE,
                start=start, length=length, shard=s, priority=base_priority,
            ))
    else:
        shard = (tensor_id * 9973) % num_shards
        parts.append(KeyPart(
            ps_key=shard * step + tensor_id * CHUNK_SPACE,
            start=0, length=size, shard=shard, priority=base_priority,
        ))
    return parts


@dataclasses.dataclass
class KeyPlan:
    """Cached encoding for a model's tensors (ref: the encode cache
    kvstore_dist.h:711-719 ps_kv_)."""

    num_shards: int
    bigarray_bound: int = 1_000_000
    slice_elems: int = 0

    def __post_init__(self):
        self._cache = {}

    def parts(self, tensor_id: int, size: int, priority: int = 0) -> List[KeyPart]:
        ent = self._cache.get(tensor_id)
        if ent is None or ent[0] != size:
            ent = (size, encode_tensor(
                tensor_id, size, self.num_shards, self.bigarray_bound,
                self.slice_elems, priority,
            ))
            self._cache[tensor_id] = ent
        return ent[1]
