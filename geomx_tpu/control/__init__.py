"""Adaptive WAN control plane (closed-loop codec/ratio autotuning).

GeoMX's WAN optimizations were all statically configured: the codec and
ratio chosen at launch stayed fixed for the whole run even as WAN
bandwidth, straggler identity, and gradient compressibility drift.  PR 3
already collects the signals needed to do better (per-codec
``wan_bytes_*`` counters, heartbeat RTT gauges, the per-round
critical-path report), and the actuation primitive
(``Ctrl.SET_COMPRESSION``) existed but was only ever invoked at setup
time.  This package closes the loop:

- :mod:`signals` — sliding-window estimators over the existing
  observability (goodput from registry byte deltas, heartbeat RTT,
  WAN round rate, the trace collector's ``dominant_stage``).
- :mod:`policy` — a deadband-and-cooldown hysteresis engine mapping a
  target round budget to a codec tier
  (``none → fp16 → bsc(r) → bsc(r/4) → 2bit``, MPQ size-bound retuning),
  constraint-aware via the shared ``compression_allowed`` predicate
  (TS overlay forbids bsc/mpq; HFA forbids non-weight-safe codecs).
- :mod:`controller` — the epoch-fenced reconfiguration protocol:
  ``Ctrl.SET_WAN_POLICY {epoch, compression}`` broadcast down both
  tiers, applied atomically at round boundaries, with cross-epoch
  payloads fenced by receivers and transparently re-encoded + retried
  by senders.

See docs/adaptive-wan.md for the protocol and tuning-knob reference.
"""

from geomx_tpu.control.controller import AdaptiveWanController  # noqa: F401
from geomx_tpu.control.policy import WanPolicyEngine, build_ladder  # noqa: F401
from geomx_tpu.control.signals import SignalEstimator, WanSignals  # noqa: F401
