#!/usr/bin/env bash
# Distributed-tracing demo + CI guard: a short in-proc HiPS simulation
# (2 parties x 2 workers, 1 global server) with trace_sample_every=1,
# training the demo CNN for a few rounds.  Asserts the merged trace is
# non-empty, spans from >= 3 node roles are causally connected, and the
# critical-path report names a dominant stage per round — then leaves
# the artifacts in ${GEOMX_TRACE_DIR:-/tmp/geomx_trace_demo} for
# chrome://tracing / https://ui.perfetto.dev.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
OUT="${GEOMX_TRACE_DIR:-/tmp/geomx_trace_demo}"
mkdir -p "$OUT"

python - "$OUT" <<'PY'
import json
import sys

import jax
import numpy as np

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.data import ShardedIterator, synthetic_classification
from geomx_tpu.kvstore import Simulation
from geomx_tpu.models import create_cnn_state
from geomx_tpu.training import run_worker

out_dir = sys.argv[1]
sim = Simulation(Config(topology=Topology(num_parties=2,
                                          workers_per_party=2),
                        trace_sample_every=1))
try:
    ws = sim.all_workers()
    ws[0].set_optimizer({"type": "sgd", "lr": 0.05})
    x, y = synthetic_classification(n=256, shape=(8, 8, 1), seed=0)
    _, params, grad_fn = create_cnn_state(jax.random.PRNGKey(0),
                                          input_shape=(1, 8, 8, 1))
    import threading

    steps = 4
    ths = [threading.Thread(target=run_worker, args=(
        kv, params, grad_fn,
        ShardedIterator(x, y, 16, i, len(ws)), steps),
        kwargs={"barrier_init": False}) for i, kv in enumerate(ws)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(240)
    assert not any(t.is_alive() for t in ths), "training hung"

    n = sim.flush_traces()
    assert n > 0, "merged trace is EMPTY"
    trace = sim.dump_trace(f"{out_dir}/geomx_trace.json")
    evs = trace["traceEvents"]
    roles = {e["pid"].split(":")[0] for e in evs}
    assert {"worker", "server", "global_server"} <= roles, roles
    ids = {e["args"]["span"] for e in evs}
    dangling = [e for e in evs
                if e["args"]["parent"] and e["args"]["parent"] not in ids]
    assert not dangling, f"{len(dangling)} dangling parent edges"
    report = sim.trace_report()
    assert report["rounds"], "critical-path report has no rounds"
    for r in report["rounds"]:
        assert r["dominant_stage"], r
    with open(f"{out_dir}/geomx_trace_report.json", "w") as f:
        json.dump(report, f, indent=1)
    print(sim.trace_collector.report_text())
    print(f"OK: {len(evs)} events across {len(roles)} roles, "
          f"{len(report['rounds'])} rounds -> {out_dir}/geomx_trace.json")
finally:
    sim.shutdown()
PY
