"""Wire format v2: self-describing raw array framing (PR 5 tentpole).

Pins the three properties the zero-copy wire path rests on:
(1) property-style roundtrip over dtypes/shapes (0-d, empty,
    non-contiguous included) — bit-exact values, preserved shape/dtype;
(2) v1↔v2 compatibility — the decoder auto-detects legacy np.save
    frames, and GEOMX_WIRE_FORMAT=v1 pins the encoder for mixed-version
    rollouts;
(3) the zero-copy guard — decoding a WRITEABLE receive buffer yields
    np.frombuffer VIEWS of it (aligned, writeable) that the servers'
    ``donated`` adopt gate takes WITHOUT a copy.  A regression back to
    copying fails here loudly, long before it shows up as wall-clock.
"""

import numpy as np
import pytest

from geomx_tpu.core.config import NodeId, Role
from geomx_tpu.transport import message as message_mod
from geomx_tpu.transport.message import Control, Domain, Message


def _msg(vals, keys=None, lens=None, **kw):
    vals = np.asarray(vals)
    if keys is None:
        keys = np.array([7], np.int64)
    if lens is None:
        lens = np.array([vals.size], np.int64)
    kw.setdefault("sender", NodeId(Role.WORKER, 1, 0))
    kw.setdefault("recipient", NodeId(Role.SERVER, 0, 0))
    return Message(keys=np.asarray(keys, np.int64), vals=vals,
                   lens=np.asarray(lens, np.int64), push=True,
                   request=True, **kw)


PAYLOADS = [
    np.arange(17, dtype=np.float32),
    np.arange(17, dtype=np.float16),
    np.arange(17, dtype=np.uint8),
    np.arange(17, dtype=np.int64),
    np.ones((), np.float32) * 2.5,          # 0-d
    np.empty(0, np.float32),                # empty
    np.arange(64, dtype=np.float32)[::2],   # non-contiguous view
    np.asfortranarray(np.arange(24, dtype=np.float32).reshape(4, 6)),
]


@pytest.mark.parametrize("arr", PAYLOADS,
                         ids=lambda a: f"{a.dtype}-{a.shape}")
def test_roundtrip_property(arr):
    m = _msg(arr, body={"num_merge": 2}, compr="fp16")
    for raw in (m.to_bytes(), m.to_bytes_v1()):
        m2 = Message.from_bytes(raw)
        assert m2.vals.dtype == arr.dtype
        assert m2.vals.shape == arr.shape
        np.testing.assert_array_equal(np.ascontiguousarray(m2.vals),
                                      np.ascontiguousarray(arr))
        assert m2.body == {"num_merge": 2} and m2.compr == "fp16"
        assert m2.sender == m.sender and m2.donated


def test_v1_frame_decodes_and_v1_pin_roundtrips(monkeypatch):
    """Old frames still decode (auto-detect), and the compat flag pins
    the ENCODER to v1 so a mixed-version rollout can upgrade either
    side first."""
    m = _msg(np.arange(9, dtype=np.float32))
    old = Message.from_bytes(m.to_bytes_v1())
    np.testing.assert_array_equal(old.vals, m.vals)
    monkeypatch.setattr(message_mod, "WIRE_V2", False)
    pinned = m.to_bytes()
    # a v1 frame leads with the positive header length, not the magic
    import struct

    (first,) = struct.unpack_from("<i", pinned, 0)
    assert first > 0
    back = Message.from_bytes(pinned)
    np.testing.assert_array_equal(back.vals, m.vals)


def test_zero_copy_deserialization_guard():
    """THE tier-1 zero-copy guard: decoded ``vals`` must be a view of
    the receive buffer — writeable (when the buffer is), 8-byte
    aligned, and adopted as-is by the server's adopt-or-copy gate."""
    from geomx_tpu.kvstore.server import _adopt_or_copy

    vals = np.arange(4096, dtype=np.float32)
    buf = bytearray(_msg(vals).to_bytes())  # the TCP recv path's buffer
    m = Message.from_bytes(buf)
    assert np.shares_memory(m.vals, np.frombuffer(buf, np.uint8)), (
        "decode copied: vals no longer aliases the receive buffer")
    assert m.vals.flags.writeable
    assert m.vals.ctypes.data % 8 == 0, "payload lost its alignment pad"
    assert m.donated
    adopted = _adopt_or_copy(m.vals, m.donated)
    assert adopted is m.vals, (
        "adopt gate copied a donated writeable wire view")
    # read-only input (a UDP datagram's bytes) must yield read-only
    # views and force the defensive copy instead
    m_ro = Message.from_bytes(bytes(buf))
    assert not m_ro.vals.flags.writeable
    assert _adopt_or_copy(m_ro.vals, m_ro.donated) is not m_ro.vals


def test_scatter_gather_frames_are_uncopied_views():
    """to_frames must hand the payload array's own memory to the
    socket layer (the no-getvalue()-copy half of the wire path)."""
    vals = np.arange(1 << 16, dtype=np.float32)
    m = _msg(vals)
    frames = m.to_frames()
    assert any(np.shares_memory(np.frombuffer(f, np.uint8), vals)
               for f in frames if not isinstance(f, bytes)), (
        "payload was copied into the frame list")
    # and the joined frames ARE the to_bytes() encoding
    joined = b"".join(bytes(f) for f in frames)
    np.testing.assert_array_equal(Message.from_bytes(joined).vals, vals)


def test_non_plain_dtypes_are_refused():
    m = _msg(np.array([object()], dtype=object),
             lens=np.array([1], np.int64))
    with pytest.raises(TypeError):
        m.to_bytes()
