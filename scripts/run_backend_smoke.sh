#!/usr/bin/env bash
# Merge-backend smoke lane: run the kvstore/failover/eviction/recovery
# test subset with the server merge lanes forced onto the JAX backend
# (GEOMX_MERGE_BACKEND shakes directly-constructed Configs too, the way
# GEOMX_SERVER_SHARDS does for the striped-merge path), so the device
# merge path cannot silently rot while tier-1 runs the numpy default.
# JAX_PLATFORMS=cpu: the point is the backend MACHINERY (staged H2D,
# donated-argument accumulate, mesh psum under the virtual 8-device
# conftest mesh), not accelerator hardware.
#
# Since ISSUE 11 the sweep runs with the DEVICE OPTIMIZER STAGE on
# (GEOMX_MERGE_OPT_DEVICE=1, the default — pinned here so a default
# flip can't silently shrink the lane) and includes the checkpoint/
# restore and device-optimizer suites: every failover, eviction,
# reassignment and warm-boot path runs with device-resident weights +
# moments, proving the export_state/import_state snapshot hooks carry
# the trajectory across all of them.
#
# Since ISSUE 20 the sweep also pins the DEVICE CODEC STAGE on
# (GEOMX_CODEC_DEVICE=1, the default) and adds the codec / adaptive-WAN
# / device-codec suites: every compression rung (fp16/2bit/bsc/mpq)
# encodes from the device accumulator and decodes into device merge
# buffers, with the numpy codecs cross-checked bitwise by
# tests/test_device_codec.py.
#
# Env: PYTEST_ARGS (extra pytest flags), GEOMX_MERGE_BACKEND (default jax),
#      GEOMX_MERGE_OPT_DEVICE (default 1), GEOMX_CODEC_DEVICE (default 1)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_PLATFORM_NAME=cpu
export GEOMX_MERGE_BACKEND=${GEOMX_MERGE_BACKEND:-jax}
export GEOMX_MERGE_OPT_DEVICE=${GEOMX_MERGE_OPT_DEVICE:-1}
export GEOMX_CODEC_DEVICE=${GEOMX_CODEC_DEVICE:-1}

exec python -m pytest -q -m 'not slow' -p no:cacheprovider \
  tests/test_kvstore.py tests/test_failover.py tests/test_eviction.py \
  tests/test_sharded_merge.py tests/test_recovery.py \
  tests/test_sharded_global.py \
  tests/test_merge_backend.py tests/test_device_opt.py \
  tests/test_compression.py tests/test_adaptive_wan.py \
  tests/test_device_codec.py \
  ${PYTEST_ARGS:-}
