from geomx_tpu.native.bindings import lib, available  # noqa: F401
