"""Cross-tier distributed tracing over the HiPS tree.

Causal spans (trace_id / span_id / parent_span_id carried on every
``Message``) connect one synchronization round's
push → local-merge → WAN → global-merge → pull chain across every node
role; a collector on the global scheduler merges all parties' spans into
one Chrome-trace/perfetto timeline (clock-corrected from heartbeat RTTs)
and distills a per-round critical-path report.

Off by default (``Config.trace_sample_every = 0``): every hot-path hook
gates on one module flag and the span factory returns a shared no-op, so
the disabled path adds no per-message work.  Sampling every N-th round
bounds the overhead when it is on.

See docs/tracing.md for usage.
"""

from geomx_tpu.trace import context
from geomx_tpu.trace.context import (TraceContext, activate, new_span_id,
                                     trace_id_for_round)
from geomx_tpu.trace.recorder import Tracer, get_tracer


def get_collector(postoffice):
    """Construct the scheduler-side collector (lazy import: the
    collector pulls in the ps layer, which instruments back into us)."""
    from geomx_tpu.trace.collector import TraceCollector

    return TraceCollector(postoffice)


class PhaseTracer:
    """Test/soak helper: bracket coarse phases of a long-running test as
    root spans so a flake's dumped timeline shows which phase stalled.

    Activates tracing (phases are always sampled), records each phase as
    its own root trace on a synthetic node, and ``dump()`` writes a
    self-contained Chrome-trace JSON artifact.
    """

    def __init__(self, name: str):
        activate()
        self.name = name
        self.tracer = get_tracer(f"test:{name}")
        self._n = 0
        self._open = None

    def phase(self, label: str):
        self._n += 1
        span = self.tracer.round(self._n - 1, 1)
        span.name = f"phase.{label}"
        return span

    def begin(self, label: str) -> None:
        """Linear alternative to ``with phase(...)`` for long soak
        bodies: closes the previous phase and opens the next — no
        re-indentation of existing test code."""
        self.end()
        self._open = self.phase(label)
        self._open.__enter__()

    def end(self) -> None:
        if self._open is not None:
            self._open.__exit__(None, None, None)
            self._open = None

    def mark(self, label: str, **extra):
        self.tracer.instant(f"mark.{label}", **extra)

    def dump(self, path: str = "") -> str:
        """Write the phase timeline artifact; defaults under
        $GEOMX_TEST_TRACE_DIR (or /tmp/geomx_trace_tests)."""
        self.end()
        if not path:
            import os

            d = os.environ.get("GEOMX_TEST_TRACE_DIR",
                               "/tmp/geomx_trace_tests")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{self.name}.json")
        self.tracer.profiler.dump(path)
        return path


__all__ = ["TraceContext", "Tracer", "PhaseTracer", "activate",
           "context", "get_collector", "get_tracer", "new_span_id",
           "trace_id_for_round"]
