"""INT8 post-training quantization (ref: src/operator/quantization/ —
quantize/dequantize/quantized_fully_connected + calibration flow)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from geomx_tpu.ops import (int8_matmul, make_quantized_mlp_apply,
                           quantize_dense_tree, quantize_symmetric)


def test_quantize_symmetric_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    q, scale = quantize_symmetric(x)
    assert q.dtype == jnp.int8
    err = np.abs(np.asarray(q, np.float32) * np.asarray(scale) - x)
    assert err.max() <= float(scale) / 2 + 1e-6  # half-ulp rounding


def test_quantize_per_channel_scales():
    x = jnp.asarray([[1.0, 100.0], [0.5, -50.0]], jnp.float32)
    q, scale = quantize_symmetric(x, axis=0)
    assert scale.shape == (1, 2)
    np.testing.assert_allclose(np.asarray(scale)[0],
                               [1.0 / 127, 100.0 / 127], rtol=1e-6)


def test_int8_matmul_close_to_fp32():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
    wq, ws = quantize_symmetric(w, axis=0)
    out = jax.jit(int8_matmul)(x, wq, ws)
    ref = x @ w
    # int8 dynamic quantization: ~1% relative error on gaussian data
    rel = np.abs(np.asarray(out) - np.asarray(ref)).mean() / np.abs(
        np.asarray(ref)).mean()
    assert rel < 0.02, rel
    assert out.dtype == jnp.float32


def test_quantized_mlp_matches_fp32_accuracy():
    """Post-training int8 inference keeps the trained MLP's accuracy on
    the synthetic task (the reference's calibration acceptance style)."""
    from geomx_tpu.data import synthetic_classification
    from geomx_tpu.models import create_model_state

    model, params, grad_fn = create_model_state(
        "mlp", jax.random.PRNGKey(0), input_shape=(1, 8, 8, 1))
    x, y = synthetic_classification(n=512, shape=(8, 8, 1), seed=0)
    xs, ys = jnp.asarray(x), jnp.asarray(y.astype(np.int32))
    # train fp32 briefly
    for _ in range(30):
        _, _, grads = grad_fn(params, xs[:128], ys[:128])
        params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                        params, grads)
    fp_logits = model.apply(params, xs)
    fp_acc = float((jnp.argmax(fp_logits, -1) == ys).mean())

    # numpy leaves (the kvstore-pull shape) must quantize too
    params_np = jax.tree_util.tree_map(np.asarray, params)
    qtree = quantize_dense_tree(params_np)
    q_apply = jax.jit(make_quantized_mlp_apply())
    q_logits = q_apply(qtree, xs)
    q_acc = float((jnp.argmax(q_logits, -1) == ys).mean())
    assert fp_acc > 0.8  # the task is learnable
    assert q_acc >= fp_acc - 0.03, (fp_acc, q_acc)
    # and the kernels really are int8
    flat = jax.tree_util.tree_leaves(
        qtree, is_leaf=lambda l: isinstance(l, dict) and "q" in l)
    assert any(isinstance(l, dict) and l["q"].dtype == jnp.int8
               for l in flat)
