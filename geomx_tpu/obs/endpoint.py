"""Shared scheduler-side telemetry endpoint.

The global scheduler serves no data traffic, so its PS app id is free
for telemetry: ``Ctrl.TRACE_REPORT`` (PR 3), ``Ctrl.METRICS_REPORT``
and ``Ctrl.CLUSTER_STATE`` frames all arrive as requests on
``(APP_PS, customer 0)``.  A Customer can only register once per
postoffice, so every collector shares ONE endpoint that routes inbound
frames by their ``Ctrl`` head: :func:`get_endpoint` is get-or-create on
the postoffice, and ``acquire``/``release`` refcount the customer's
lifetime — the trace collector, metrics collector and cluster-state
service stop independently, in any order.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict


class TelemetryEndpoint:
    """Owner of the PS app on a scheduler postoffice; routes request
    frames to the handler registered for their ``cmd`` head."""

    def __init__(self, postoffice):
        from geomx_tpu.kvstore.common import APP_PS
        from geomx_tpu.ps.customer import Customer

        self.po = postoffice
        self._mu = threading.Lock()
        self._routes: Dict[int, Callable] = {}
        self._refs = 0
        self._stopped = False
        self._customer = Customer(APP_PS, 0, self._on_msg, postoffice,
                                  owns_app=True)

    def route(self, cmd, handler: Callable) -> None:
        """Register ``handler(msg)`` for request frames with this head."""
        with self._mu:
            self._routes[int(cmd)] = handler

    def _on_msg(self, msg):
        if not msg.request:
            return
        with self._mu:
            fn = self._routes.get(int(msg.cmd))
        if fn is not None:
            fn(msg)
        # anything else addressed at the scheduler's PS app is dropped —
        # the scheduler serves no data traffic

    def acquire(self) -> "TelemetryEndpoint":
        with self._mu:
            self._refs += 1
        return self

    def release(self) -> None:
        """Drop one reference; the customer stops when the last
        collector has released (idempotent past that point)."""
        with self._mu:
            self._refs -= 1
            if self._refs > 0 or self._stopped:
                return
            self._stopped = True
        self._customer.stop()


def get_endpoint(postoffice) -> TelemetryEndpoint:
    """Get-or-create the postoffice's shared telemetry endpoint (one
    per postoffice for its whole lifetime — Customer registrations are
    permanent).  Callers ``acquire()`` it and ``release()`` on stop."""
    ep = getattr(postoffice, "_telemetry_endpoint", None)
    if ep is None:
        ep = TelemetryEndpoint(postoffice)
        postoffice._telemetry_endpoint = ep
    return ep
