"""Postoffice: per-node runtime hub — node table, dispatch, barriers, key ranges.

Mirrors the responsibilities of the reference Postoffice (ref:
ps-lite/include/ps/internal/postoffice.h:35-76, src/postoffice.cc) — role
bookkeeping, node-group membership, scheduler-counted barriers for both the
local and the global domain (ref: postoffice.cc:202-244,
van.cc:259-288 ProcessBarrierCommand), and server key ranges
(ref: postoffice.cc:246-259 GetServerKeyRanges).

Divergence from the reference: node discovery is static (the Topology is
known up front) rather than via ADD_NODE registration; dynamic
join/recovery is layered on top for the TCP fabric (see
transport/heartbeat in the aux subsystem).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple

from geomx_tpu.core.config import Config, Group, NodeId, Role, Topology
from geomx_tpu.obs.flight import FlightEv
from geomx_tpu.trace import context as _tctx
from geomx_tpu.transport.message import Control, Domain, Message
from geomx_tpu.transport.van import InProcFabric, Van

# The ps key space. Tensor ids are encoded into this space by the kvstore
# layer; servers own contiguous ranges of it (ref: ps/base.h kMaxKey).
MAX_KEY = 1 << 62


@dataclasses.dataclass(frozen=True)
class KeyRange:
    begin: int  # inclusive
    end: int    # exclusive

    def contains(self, key: int) -> bool:
        return self.begin <= key < self.end


def split_range(n: int, total: int = MAX_KEY) -> List[KeyRange]:
    """Equal partition of the key space across n servers
    (ref: postoffice.cc:246-259)."""
    step = total // n
    out = []
    for i in range(n):
        end = total if i == n - 1 else (i + 1) * step
        out.append(KeyRange(i * step, end))
    return out


class Postoffice:
    """One per node. Owns the Van, routes messages, runs barriers.

    Customers register with (app_id, customer_id); data messages are routed
    to them. Control messages (BARRIER, HEARTBEAT, TS scheduling) are
    handled here or forwarded to registered control hooks.
    """

    def __init__(
        self,
        node: NodeId,
        topology: Topology,
        fabric: InProcFabric,
        config: Optional[Config] = None,
    ):
        self.node = node
        self.topology = topology
        self.config = config or Config()
        if self.config.trace_sample_every > 0:
            # flip the process-wide tracing gate once; everything else
            # (sampling, span recording) keys off per-round contexts
            _tctx.activate()
        self.van = Van(
            node,
            fabric,
            config=self.config,
            use_priority_queue=self.config.enable_p3,
        )
        self.flight = None  # black-box recorder, built below
        self._customers: Dict[Tuple[int, int], "Customer"] = {}
        self._app_owner: Dict[int, "Customer"] = {}
        self._control_hooks: List[Callable[[Message], bool]] = []
        self._lock = threading.Lock()
        # barrier state
        self._barrier_cv = threading.Condition()
        self._barrier_done: Dict[int, bool] = {}
        self._barrier_seq = 0
        # scheduler-side barrier counting: (group_token) -> list of waiters
        self._barrier_waiting: Dict[str, List[Message]] = {}
        # heartbeat bookkeeping (scheduler side: last-seen per node,
        # ref: Van::ProcessHeartbeat van.cc:242-257, UpdateHeartbeat).
        # ``_hb_boots`` records each sender's Van incarnation nonce so the
        # eviction actuator can fence the exact incarnation it declared
        # dead (kvstore/eviction.py)
        self._heartbeats: Dict[str, float] = {}
        self._hb_boots: Dict[str, int] = {}
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_task = None  # reactor timer-wheel entry (reactor mode)
        self._hb_stop = threading.Event()
        self._hb_epoch = 0.0
        self._dead_replies: Dict[int, dict] = {}
        # clock-offset estimation (non-scheduler side): heartbeats carry
        # a send stamp, the scheduler echoes it with its own receive
        # stamp, and the classic RTT/2 estimate gives "scheduler clock
        # minus mine" per scheduler target — what the trace collector
        # uses to merge per-node span timestamps onto one timeline
        self._clock_offsets: Dict[str, float] = {}
        self._hb_rtts: Dict[str, float] = {}
        self._hb_echo_t: Dict[str, float] = {}  # last echo arrival per
        #                                         scheduler (monotonic)
        self._rtt_gauge = None
        self._offset_gauge = None
        self._tracer = None
        # scheduler-side barrier exclusion: members declared dead by the
        # eviction monitor stop counting toward barrier quorums, so FSA
        # degrades to the survivor set instead of timing out
        self._excluded: set = set()
        # SWIM-style indirect-probe relays in flight FROM this node
        # (Control.PROBE_INDIRECT): relay token -> Event set when the
        # suspect's pong lands (kvstore/eviction.py drives these)
        self._probe_pending: Dict[str, threading.Event] = {}
        self._started = False
        # black-box flight recorder (geomx_tpu/obs/flight): DEFAULT ON —
        # a fixed-size per-node event ring tapped by the van (message
        # heads, dedup), this postoffice (barriers), and the server /
        # monitor roles (fences, folds, promotions, rounds); dumps to
        # GEOMX_OBS_DIR on exit / health alert / operator request.
        # Disabled (GEOMX_FLIGHT=0): nothing constructed, every tap is
        # one attribute check.
        if getattr(self.config, "enable_flight", True):
            from geomx_tpu.obs.flight import FlightRecorder

            self.flight = FlightRecorder(str(node), self.config,
                                         postoffice=self)
            self.van.flight = self.flight
            self.add_control_hook(self.flight.on_control)
            self.flight.add_pressure("van_sendq_depth",
                                     self.van._pq.qsize)
            # scheduler pressure: total OS threads in the process (the
            # reading the reactor refactor exists to flatten — O(nodes)
            # under the thread-per-endpoint harness, O(1) under the
            # reactor) and, when this fabric rides the shared reactor,
            # its loop-lag / fd-count health
            self.flight.add_pressure("process_threads",
                                     threading.active_count)
            reactor = getattr(fabric, "reactor", None)
            if reactor is not None:
                self.flight.add_pressure("reactor_loop_lag_ms",
                                         reactor.loop_lag_ms)
                self.flight.add_pressure("reactor_fds", reactor.fd_count)

    # ---- lifecycle ----------------------------------------------------------
    def start(self):
        if not self._started:
            self.van.start(self._dispatch)
            self._started = True
            import time as _time

            self._hb_epoch = _time.monotonic()
            if (self.config.heartbeat_interval_s > 0
                    and not self.node.role.is_scheduler):
                reactor = getattr(self.van.fabric, "reactor", None)
                if reactor is not None:
                    # heartbeat as a timer-wheel entry instead of a
                    # per-node sleep thread (O(100)-party harness)
                    targets = self._heartbeat_targets()
                    self._hb_task = reactor.call_every(
                        self.config.heartbeat_interval_s,
                        lambda: self._heartbeat_tick(targets),
                        name=f"heartbeat-{self.node}")
                    # the thread path pings immediately on start;
                    # call_every first fires after one interval — keep
                    # the first-contact timing identical
                    self._heartbeat_tick(targets)
                else:
                    self._hb_stop = threading.Event()
                    self._hb_thread = threading.Thread(
                        target=self._heartbeat_loop, args=(self._hb_stop,),
                        daemon=True, name=f"heartbeat-{self.node}")
                    self._hb_thread.start()

    def stop(self):
        if self._started:
            if self._hb_task is not None:
                self._hb_task.cancel()
                self._hb_task = None
            if self._hb_thread is not None:
                self._hb_stop.set()
                self._hb_thread.join(timeout=2)
                self._hb_thread = None
            self.van.stop()
            self._started = False
        if self.flight is not None:
            self.flight.stop()

    # ---- registry -----------------------------------------------------------
    def register_customer(self, customer: "Customer", owns_app: bool = False):
        """Register for message routing.

        Responses route by (app_id, customer_id) — back to the exact
        requester.  Requests route to the app *owner* (the serving
        customer), since the request carries the sender's customer_id
        (ref: van.cc ProcessDataMsg routes by app_id on non-worker nodes).
        """
        with self._lock:
            key = (customer.app_id, customer.customer_id)
            if key in self._customers:
                raise ValueError(f"duplicate customer {key} on {self.node}")
            self._customers[key] = customer
            if owns_app:
                if customer.app_id in self._app_owner:
                    raise ValueError(
                        f"duplicate app owner {customer.app_id} on {self.node}"
                    )
                self._app_owner[customer.app_id] = customer

    def add_control_hook(self, hook: Callable[[Message], bool]):
        """Hook receives control messages; return True to consume."""
        with self._lock:
            self._control_hooks.append(hook)

    def remove_control_hook(self, hook: Callable[[Message], bool]):
        """Unregister a hook added by add_control_hook (one-shot RPC
        hooks must not leak — a stale armed hook swallows the reply
        meant for a later call)."""
        with self._lock:
            try:
                self._control_hooks.remove(hook)
            except ValueError:
                pass

    # ---- dispatch -----------------------------------------------------------
    def _heartbeat_targets(self):
        """My scheduler target set.  Local servers are dual-identity and
        ping BOTH their party scheduler and the global scheduler (whose
        dead-node table covers them); workers ping the party scheduler;
        global-tier roles and replicas ping the global scheduler (the
        table makes replicas evictable and their freshness visible)."""
        targets = []
        if self.node.role in (Role.GLOBAL_SERVER, Role.STANDBY_GLOBAL,
                              Role.REPLICA):
            targets.append((self.topology.global_scheduler(), Domain.GLOBAL))
        else:
            targets.append(
                (self.topology.scheduler(self.node.party), Domain.LOCAL))
            if self.node.role is Role.SERVER:
                targets.append(
                    (self.topology.global_scheduler(), Domain.GLOBAL))
        return targets

    def _heartbeat_tick(self, targets):
        """One HEARTBEAT round to my scheduler(s) — the loop body, also
        the reactor timer-wheel entry."""
        import time as _time

        for sched, domain in targets:
            try:
                # the send stamp makes the ping echo-able: the
                # scheduler replies with (echo_t, sched_t) and this
                # node derives RTT + clock offset from the pair
                self.van.send(Message(
                    recipient=sched, control=Control.HEARTBEAT,
                    domain=domain, body={"t": _time.monotonic()}))
            except (KeyError, OSError):
                # scheduler not up yet (startup race on TCP) — a
                # transient failure must not kill the heartbeat loop
                pass

    def _heartbeat_loop(self, stop_ev: threading.Event):
        """Periodic HEARTBEAT thread (ref: van.cc:1128-1140) — the
        legacy-transport path; reactor fabrics schedule
        :meth:`_heartbeat_tick` on the shared timer wheel instead."""
        targets = self._heartbeat_targets()
        while not stop_ev.is_set():
            self._heartbeat_tick(targets)
            stop_ev.wait(self.config.heartbeat_interval_s)

    def dead_nodes(self, timeout_s: Optional[float] = None) -> List[str]:
        """Scheduler-side: nodes whose heartbeat is older than the timeout
        (ref: Postoffice::GetDeadNodes postoffice.cc:284-303)."""
        import time as _time

        assert self.node.role.is_scheduler
        if self.config.heartbeat_interval_s <= 0:
            return []  # feature off: nobody pings, so nobody is "dead"
        timeout_s = timeout_s or self.config.heartbeat_timeout_s
        now = _time.monotonic()
        with self._lock:
            expected = [
                str(n) for n in (
                    self.topology.members(
                        Group.WORKERS | Group.SERVERS, party=self.node.party)
                    if self.node.role is Role.SCHEDULER
                    else self.topology.global_servers() + self.topology.servers()
                )
            ]
            # nodes never heard from count from this scheduler's start
            return [n for n in expected
                    if now - self._heartbeats.get(n, self._hb_epoch) > timeout_s]

    def heartbeat_info(self):
        """Scheduler-side copy of the heartbeat table:
        ``({node: (last_seen_monotonic, boot)}, epoch)`` where ``epoch``
        is this scheduler's start time — the age baseline for nodes never
        heard from.  The eviction monitors (kvstore/eviction.py) sweep
        this instead of :meth:`dead_nodes` because they also watch
        out-of-plan dynamic joiners and need the ``boot`` incarnation to
        fence exactly the corpse they declared dead."""
        with self._lock:
            return ({n: (t, self._hb_boots.get(n, 0))
                     for n, t in self._heartbeats.items()},
                    self._hb_epoch)

    def uptime_s(self) -> float:
        """Seconds since this postoffice started (0.0 before start).
        QUERY_STATS and the metrics pump ship it so collectors can tell
        a warm-booted node's zeroed counters (small uptime, new boot
        nonce) from a genuine rate collapse."""
        if not self._started:
            return 0.0
        import time as _time

        return _time.monotonic() - self._hb_epoch

    def clock_offsets(self) -> Dict[str, float]:
        """Estimated scheduler-clock-minus-mine per scheduler target
        (from heartbeat echoes); {} until a first echo lands — and
        always {} on schedulers, whose clock others measure against."""
        with self._lock:
            return dict(self._clock_offsets)

    def heartbeat_rtts(self) -> Dict[str, float]:
        """Last measured heartbeat RTT per scheduler target."""
        with self._lock:
            return dict(self._hb_rtts)

    def heartbeat_echo_age(self, sched) -> float:
        """Seconds since the last heartbeat ECHO arrived from scheduler
        ``sched`` (age since this postoffice's start when none ever
        did).  The liveness view in the OTHER direction from
        :meth:`dead_nodes`: a non-scheduler node asking "can I still
        hear my scheduler?" — the degraded-mode watchdog's second
        opinion that a silent WAN link is a partition and not merely a
        slow round (kvstore/server.py)."""
        import time as _time

        now = _time.monotonic()
        with self._lock:
            base = self._hb_epoch if self._started else now
            return now - self._hb_echo_t.get(str(sched), base)

    def query_dead_nodes(self, timeout: float = 10.0) -> List[str]:
        """Ask my scheduler for its dead-node list
        (ref: kv.get_num_dead_node kvstore_dist.h:225-234)."""
        if self.node.role.is_scheduler:
            return self.dead_nodes()
        sched, domain = self._my_scheduler()
        return self._query_dead_body(sched, domain, timeout).get("dead", [])

    def _my_scheduler(self):
        sched = (self.topology.global_scheduler()
                 if self.node.role in (Role.GLOBAL_SERVER,
                                       Role.STANDBY_GLOBAL, Role.REPLICA)
                 else self.topology.scheduler(self.node.party))
        domain = (Domain.GLOBAL if sched.role is Role.GLOBAL_SCHEDULER
                  else Domain.LOCAL)
        return sched, domain

    def _query_dead_body(self, sched: NodeId, domain: Domain,
                         timeout: float, barrier_info: Optional[dict] = None,
                         ) -> dict:
        """DEAD_NODES round-trip to ``sched``; optionally asks for the
        entered-member list of one barrier token (the timeout-diagnosis
        path of :meth:`barrier`)."""
        with self._barrier_cv:
            self._barrier_seq += 1
            seq = self._barrier_seq
        self.van.send(Message(
            recipient=sched, control=Control.DEAD_NODES, domain=domain,
            request=True, timestamp=seq,
            body={"barrier": barrier_info} if barrier_info else None))
        with self._barrier_cv:
            ok = self._barrier_cv.wait_for(
                lambda: seq in self._dead_replies, timeout=timeout)
            if not ok:
                raise TimeoutError(f"{self.node}: dead-node query timed out")
            reply = self._dead_replies.pop(seq)
        return reply if isinstance(reply, dict) else {"dead": reply}

    def _dispatch(self, msg: Message):
        if msg.control is Control.DEAD_NODES:
            if msg.request:
                body = {"dead": self.dead_nodes()}
                req_b = msg.body if isinstance(msg.body, dict) else {}
                binfo = req_b.get("barrier")
                if binfo:
                    # barrier diagnosis: who already entered this token
                    token = f"{binfo['group']}@{binfo['party']}"
                    with self._lock:
                        waiting = list(self._barrier_waiting.get(token, ()))
                    body["entered"] = sorted({str(m.sender) for m in waiting})
                self.van.send(msg.reply_to(
                    control=Control.DEAD_NODES, body=body))
            else:
                with self._barrier_cv:
                    self._dead_replies[msg.timestamp] = msg.body
                    self._barrier_cv.notify_all()
            return
        if msg.control is Control.HEARTBEAT:
            import time as _time

            b = msg.body if isinstance(msg.body, dict) else {}
            if "sched_t" in b:
                # echo reply from my scheduler: RTT/2 clock estimate
                now = _time.monotonic()
                rtt = max(0.0, now - float(b["echo_t"]))
                offset = float(b["sched_t"]) - (float(b["echo_t"]) + rtt / 2)
                with self._lock:
                    self._hb_rtts[str(msg.sender)] = rtt
                    self._clock_offsets[str(msg.sender)] = offset
                    self._hb_echo_t[str(msg.sender)] = now
                    if self._rtt_gauge is None:
                        from geomx_tpu.utils.metrics import system_gauge

                        self._rtt_gauge = system_gauge(
                            f"{self.node}.heartbeat_rtt_s")
                        self._offset_gauge = system_gauge(
                            f"{self.node}.clock_offset_s")
                self._rtt_gauge.set(rtt)
                self._offset_gauge.set(offset)
                return
            with self._lock:
                self._heartbeats[str(msg.sender)] = _time.monotonic()
                self._hb_boots[str(msg.sender)] = msg.boot
            if "t" in b:
                try:
                    self.van.send(msg.reply_to(
                        control=Control.HEARTBEAT,
                        body={"echo_t": b["t"],
                              "sched_t": _time.monotonic()}))
                except (KeyError, OSError):
                    pass  # sender vanished between ping and echo
            return
        if msg.control is Control.BARRIER:
            self._handle_barrier(msg)
            return
        if msg.control is Control.ADDR_UPDATE:
            # a replacement node at a new host:port announced itself
            # (ref: re-registration ADD_NODE van.cc:176-193; here the
            # node broadcasts directly since the plan names every peer)
            b = msg.body or {}
            update = getattr(self.van.fabric, "update_address", None)
            if update is not None:
                update(b["node"], (b["host"], int(b["port"])))
            return
        if msg.control is Control.PROBE_INDIRECT:
            if self._handle_probe_indirect(msg):
                return
            # not consumed: a relay's {alive} verdict falls through to
            # the control hooks — the monitor's actuator collects it by
            # token exactly like EVICT/REJOIN replies
        if msg.control is not Control.EMPTY:
            with self._lock:
                hooks = list(self._control_hooks)
            for hook in hooks:
                if hook(msg):
                    return
            return
        if msg.request:
            cust = self._app_owner.get(msg.app_id) or self._customers.get(
                (msg.app_id, msg.customer_id)
            )
        else:
            cust = self._customers.get((msg.app_id, msg.customer_id))
        if cust is None:
            raise KeyError(
                f"{self.node}: no customer ({msg.app_id},{msg.customer_id}) "
                f"request={msg.request} for message from {msg.sender}"
            )
        cust.accept(msg)

    # ---- SWIM-style indirect probes (Control.PROBE_INDIRECT) ---------------
    def _handle_probe_indirect(self, msg: Message) -> bool:
        """Three legs, all stateless beyond ``_probe_pending``:

        * request ``{ping}`` → answer ``{pong}`` inline (pure liveness
          — nothing else is touched, so a quarantined node still pongs);
        * request ``{suspect, timeout}`` → relay a ping to the suspect
          on a short-lived thread (the van send + wait would block the
          dispatch/handler thread — reactor-blocking lint) and reply
          ``{alive, suspect, token}`` to the asking monitor;
        * response ``{pong}`` → complete the pending relay by token.

        Returns False for the one leg it does NOT consume: an ``{alive}``
        relay verdict, which the monitor's control hook collects."""
        b = msg.body if isinstance(msg.body, dict) else {}
        if msg.request and b.get("ping"):
            try:
                self.van.send(msg.reply_to(body={"pong": True,
                                                 "token": b.get("token")}))
            except (KeyError, OSError):
                pass  # asker vanished between ping and pong
            return True
        if msg.request and "suspect" in b:
            t = threading.Thread(
                target=self._relay_probe, args=(msg,),
                name=f"probe-relay-{self.node}", daemon=True)
            t.start()
            return True
        if not msg.request and "pong" in b:
            with self._lock:
                ev = self._probe_pending.get(b.get("token"))
            if ev is not None:
                ev.set()
            return True
        return False

    def _relay_probe(self, msg: Message):
        import uuid

        b = msg.body if isinstance(msg.body, dict) else {}
        timeout = float(b.get("timeout") or self.config.probe_timeout_s)
        token = f"{self.node}#probe-{uuid.uuid4().hex[:8]}"
        ev = threading.Event()
        with self._lock:
            self._probe_pending[token] = ev
        alive = False
        try:
            self.van.send(Message(
                recipient=NodeId.parse(str(b["suspect"])),
                control=Control.PROBE_INDIRECT, domain=msg.domain,
                request=True, body={"ping": True, "token": token}))
            alive = ev.wait(timeout)
        except (KeyError, OSError):
            alive = False  # no route to the suspect = dead from here
        finally:
            with self._lock:
                self._probe_pending.pop(token, None)
        try:
            self.van.send(msg.reply_to(
                body={"alive": bool(alive), "suspect": str(b["suspect"]),
                      "token": b.get("token")}))
        except (KeyError, OSError):
            pass  # the asking monitor vanished mid-probe

    # ---- barriers -----------------------------------------------------------
    def _scheduler_for(self, group: Group) -> NodeId:
        if group & (Group.GLOBAL_SERVERS | Group.GLOBAL_WORKERS | Group.GLOBAL_SCHEDULER):
            return self.topology.global_scheduler()
        assert self.node.party is not None, f"{self.node} has no party for local barrier"
        return self.topology.scheduler(self.node.party)

    def barrier(self, group: Group, timeout: Optional[float] = 60.0):
        """Block until every member of `group` has entered the barrier.

        Counted at the scheduler like the reference (ref: postoffice.cc:202-244).
        The caller must be a member of `group`.
        """
        sched = self._scheduler_for(group)
        # party only scopes local-domain groups; global groups span parties
        is_global = sched.role is Role.GLOBAL_SCHEDULER
        party = None if is_global else self.node.party
        members = self.topology.members(group, party=self.node.party)
        assert self.node in members, f"{self.node} not in barrier group {group}"
        if len(members) <= 1:
            return
        with self._barrier_cv:
            self._barrier_seq += 1
            seq = self._barrier_seq
        domain = Domain.GLOBAL if is_global else Domain.LOCAL
        req = Message(
            recipient=sched, control=Control.BARRIER, domain=domain, request=True,
            body={"group": group.value, "party": party, "seq": seq},
        )
        fl = self.flight
        if fl is not None:
            fl.record(FlightEv.BARRIER_ENTER, a=group.value, b=seq,
                      peer=sched)
        if _tctx.ACTIVE and _tctx.current() is not None:
            # barrier waits inside a sampled round are a first-class
            # critical-path stage (FSA stalls ARE barrier time)
            if self._tracer is None:
                from geomx_tpu.trace.recorder import get_tracer

                self._tracer = get_tracer(str(self.node))
            with self._tracer.span("barrier.wait"):
                self.van.send(req)
                with self._barrier_cv:
                    ok = self._barrier_cv.wait_for(
                        lambda: self._barrier_done.pop(seq, False),
                        timeout=timeout)
        else:
            self.van.send(req)
            with self._barrier_cv:
                ok = self._barrier_cv.wait_for(
                    lambda: self._barrier_done.pop(seq, False),
                    timeout=timeout)
        if fl is not None:
            fl.record(FlightEv.BARRIER_RELEASE if ok
                      else FlightEv.BARRIER_TIMEOUT,
                      a=group.value, b=seq, peer=sched)
        if not ok:
            # diagnosable stall: ask the scheduler who is dead and who
            # never entered this token, so the exception alone names the
            # culprit.  Best-effort — a dead scheduler degrades to the
            # bare message
            detail = ""
            try:
                body = self._query_dead_body(
                    sched, domain,
                    timeout=min(5.0, timeout or 5.0),
                    barrier_info={"group": group.value, "party": party})
                entered = set(body.get("entered", ()))
                missing = sorted(str(m) for m in members
                                 if str(m) not in entered
                                 and m != self.node)
                detail = (f" (scheduler dead-node list: "
                          f"{body.get('dead', [])}; members that never "
                          f"entered: {missing})")
            except Exception:
                pass
            raise TimeoutError(
                f"{self.node}: barrier on {group} timed out{detail}")

    def exclude_node(self, node_s: str):
        """Scheduler-side (eviction actuator): drop a dead member from
        barrier accounting and release every barrier that is now
        satisfied without it — waiting survivors must not ride out the
        full timeout for a corpse that can never enter."""
        assert self.node.role.is_scheduler
        to_release: List[Message] = []
        with self._lock:
            self._excluded.add(node_s)
            for token in list(self._barrier_waiting):
                waiting = self._barrier_waiting[token]
                if len(waiting) >= len(self._alive_members_locked(token)):
                    to_release.extend(self._barrier_waiting.pop(token))
        if to_release and self.flight is not None:
            self.flight.record(FlightEv.BARRIER_RELEASE,
                               c=len(to_release), peer=node_s,
                               note="eviction_release")
        for req in to_release:
            self.van.send(req.reply_to(body={"seq": req.body["seq"]}))

    def readmit_node(self, node_s: str):
        """Inverse of :meth:`exclude_node` — an evicted member rejoined
        (membership broadcast names it again), so it counts toward
        barrier quorums once more."""
        with self._lock:
            self._excluded.discard(node_s)

    def _alive_members_locked(self, token: str) -> List[NodeId]:
        """Barrier quorum for ``token`` minus evicted members (caller
        holds ``_lock``)."""
        gval, pval = token.rsplit("@", 1)
        group = Group(int(gval))
        party = None if pval == "None" else int(pval)
        members = self.topology.members(group, party=party)
        return [m for m in members if str(m) not in self._excluded]

    def _handle_barrier(self, msg: Message):
        if msg.request:
            # scheduler side: count entries for this (group, party);
            # evicted members don't count toward the quorum
            assert self.node.role.is_scheduler, f"{self.node} got barrier request"
            group = Group(msg.body["group"])
            party = msg.body["party"]
            token = f"{group.value}@{party}"
            fl = self.flight
            with self._lock:
                alive = self._alive_members_locked(token)
                waiting = self._barrier_waiting.setdefault(token, [])
                waiting.append(msg)
                entered, quorum = len(waiting), len(alive)
                if entered < quorum:
                    if fl is not None:
                        # the scheduler's view is the forensic one: who
                        # entered, and how many the token still waits on
                        fl.record(FlightEv.BARRIER_ENTER, a=group.value,
                                  b=entered, c=quorum, peer=msg.sender)
                    return
                released = self._barrier_waiting.pop(token)
            if fl is not None:
                fl.record(FlightEv.BARRIER_RELEASE, a=group.value,
                          c=len(released), peer=msg.sender)
            for req in released:
                self.van.send(req.reply_to(body={"seq": req.body["seq"]}))
        else:
            with self._barrier_cv:
                self._barrier_done[msg.body["seq"]] = True
                self._barrier_cv.notify_all()

    # ---- key ranges ---------------------------------------------------------
    def server_key_ranges(self, is_global: bool = False) -> List[KeyRange]:
        """Key ranges of tier-1 (one local server) or tier-2 (M global servers)
        (ref: postoffice.cc:246-259; GetServerKeyRanges(is_global))."""
        if is_global:
            return split_range(self.topology.num_global_servers)
        return split_range(1)

    def server_for_key(self, key: int, is_global: bool = False) -> int:
        ranges = self.server_key_ranges(is_global)
        step = MAX_KEY // len(ranges)
        return min(key // step, len(ranges) - 1)
