"""Read-serving replica tier: staleness-bounded model subscribers
serving high-QPS pull/predict traffic under concurrent training.

See docs/serving.md for the operator guide.
"""

from geomx_tpu.serve.client import ReplicaClient
from geomx_tpu.serve.monitor import ReplicaMonitor
from geomx_tpu.serve.replica import ModelReplica

__all__ = ["ModelReplica", "ReplicaClient", "ReplicaMonitor"]
