#!/usr/bin/env bash
# Dynamic membership acceptance: a 1x2 party trains while an OUT-OF-PLAN
# third worker joins mid-training (ADD_NODE), trains a couple of rounds,
# and leaves gracefully (ref: runtime id assignment van.cc:41-112).
#
# MODE=tsengine or MODE=hfa runs the same join under the TS overlay /
# the HFA weight-averaging loop (r5: membership is uniform across
# modes, like the reference's ADD_NODE).
set -euo pipefail
MODE="${MODE:-}"
EXTRA=()
case "$MODE" in
  tsengine) EXTRA+=(--tsengine) ;;
  hfa)      EXTRA+=(--hfa) ;;
  "")       ;;
  *) echo "unknown MODE='$MODE' (want tsengine|hfa|empty)" >&2; exit 2 ;;
esac
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
BASE_PORT="${BASE_PORT:-9400}"
STEPS="${STEPS:-8}"
# the joiner's rounds must be a PREFIX of the cluster's (it folds into
# the count; rounds past the cluster's last would stall against
# terminated servers) — clamp its steps under the cluster's
JOIN_STEPS=2
if [ "$STEPS" -lt 3 ]; then JOIN_STEPS=1; fi

PARTIES=1 WORKERS=2 STEPS="$STEPS" BASE_PORT="$BASE_PORT" \
  "$HERE/run_cluster.sh" "${EXTRA[@]}" &
CLUSTER=$!
# a joiner crash must not orphan the 6 cluster processes (they would
# hold the ports forever waiting for the dead joiner's rounds)
trap 'kill "$CLUSTER" 2>/dev/null || true' EXIT
sleep 2
python -m geomx_tpu.launch --role worker:2@p0 --parties 1 --workers 2 \
  --base-port "$BASE_PORT" --steps "$JOIN_STEPS" --join \
  "${EXTRA[@]}" \
  --advertise "127.0.0.1:$((BASE_PORT + 40))"
wait "$CLUSTER"
trap - EXIT
