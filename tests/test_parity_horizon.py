"""Long-horizon convergence parity — the reference's acceptance
criterion promoted to a gate (VERDICT r4 item 3; ref: examples/cnn.py:
128-131 prints per-iteration test accuracy, SURVEY §4.3
convergence-as-oracle).

Each WAN feature trains the identical model/data/seed for 200 steps
through the 2-party HiPS stack; its FINAL held-out accuracy must land
within its ε of the vanilla run's.  This is where BSC's residual
cycling, HFA's milestone staleness and DGT's lossy tail would show up —
they are invisible at the 8-step horizon of the per-codec loss oracle
(tests/test_e2e_cnn.py::test_codec_convergence_parity)."""

import numpy as np
import pytest

from geomx_tpu.utils.parity import PARITY_CONFIGS, run_parity_matrix


@pytest.mark.slow
def test_long_horizon_accuracy_parity():
    results = run_parity_matrix(steps=200)
    assert "error" not in results["vanilla"], results["vanilla"]
    van = results["vanilla"]["final_accuracy"]
    # the synthetic task must be learnable-but-not-saturating, or every
    # delta below is vacuous
    assert 0.45 < van <= 0.995, results["vanilla"]
    failures = {}
    for name, spec in PARITY_CONFIGS.items():
        if name == "vanilla":
            continue
        r = results[name]
        if "error" in r:
            failures[name] = r["error"]
            continue
        # the feature must actually have engaged: codecs shrink WAN
        # bytes, hfa shrinks WAN rounds — all leave a byte signature
        if name in ("fp16", "2bit", "bsc", "mpq", "hfa_k2_8"):
            if not r["wan_send_bytes"] < 0.9 * results["vanilla"][
                    "wan_send_bytes"]:
                failures[name] = f"feature did not engage: {r}"
                continue
        if r["final_accuracy"] < van - spec["eps"]:
            failures[name] = (f"final acc {r['final_accuracy']} < "
                              f"vanilla {van} - eps {spec['eps']}")
    assert not failures, failures
    # every config genuinely learned (not just 'close to a bad vanilla')
    for name, r in results.items():
        if "final_accuracy" in r:
            assert r["final_accuracy"] > 0.4, (name, r)
            assert np.isfinite(r["final_loss"]), (name, r)
