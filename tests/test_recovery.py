"""Elastic mid-round recovery: application-level request replay, server
replay dedup, automatic checkpoints, and full-process crash/restart of a
global server (the reference's recovery is scheduler id-reassignment
only, van.cc:176-193, and its global tier recovery is a TODO,
van.cc:224 — this build improves on it with checkpoints + replay)."""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.common import RecentRequests
from geomx_tpu.ps import KVPairs, KVServer, KVWorker, Postoffice
from geomx_tpu.ps.postoffice import split_range
from geomx_tpu.transport import InProcFabric, Message


class _Msg:
    def __init__(self, sender, ts, app_id=0, customer_id=0, boot=0):
        self.sender = sender
        self.timestamp = ts
        self.app_id = app_id
        self.customer_id = customer_id
        self.boot = boot


def test_recent_requests_window():
    r = RecentRequests(cap=4)
    m = _Msg("a", 1)
    assert r.check(m) == "new"
    assert r.check(m) == "pending"
    r.mark_done(m)
    assert r.check(m) == "done"
    assert r.check(_Msg("b", 1)) == "new"       # distinct sender
    assert r.check(_Msg("a", 2)) == "new"       # distinct ts
    for i in range(10, 16):                      # overflow the window
        r.check(_Msg("c", i))
    assert r.check(m) == "new"                   # evicted → reconsidered


def _mini_cluster(cfg):
    topo = cfg.topology
    fabric = InProcFabric()
    offices = {str(n): Postoffice(n, topo, fabric, cfg)
               for n in topo.all_nodes()}
    for po in offices.values():
        po.start()
    return topo, fabric, offices


def test_request_retry_resends_unanswered_push():
    """A push whose first copy is swallowed (simulating state lost in a
    crash) is replayed after request_retry_s and then answered."""
    cfg = Config(topology=Topology(num_parties=1, workers_per_party=1),
                 request_retry_s=0.3)
    topo, fabric, offices = _mini_cluster(cfg)
    applied = []
    dropped_first = []

    def handle(msg, kvs, server):
        if msg.push:
            if not dropped_first:
                dropped_first.append(True)  # crash: state + request lost
                return
            applied.append(np.array(kvs.vals))
            server.response(msg)

    sn = topo.server(0)
    server = KVServer(0, 0, offices[str(sn)], handle)
    w = topo.workers(0)[0]
    kw = KVWorker(0, 1, offices[str(w)], [sn], split_range(1))
    ts = kw.zpush(KVPairs(np.array([1]), np.ones(8, np.float32),
                          np.array([8])))
    kw.wait(ts)  # completes only via the replay
    assert len(applied) == 1
    np.testing.assert_array_equal(applied[0], np.ones(8, np.float32))
    kw.stop(); server.stop()
    for po in offices.values():
        po.stop()
    fabric.shutdown()


def test_duplicate_responses_do_not_complete_early():
    """Two servers; server A answers twice (replay re-ack) while server B
    is slow — the duplicate from A must not complete the request before
    B answers."""
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=1),
                 request_retry_s=5.0)  # long: no actual replay this test
    topo, fabric, offices = _mini_cluster(cfg)
    b_release = threading.Event()
    sa, sb = topo.server(0), topo.server(1)

    def make_handle(double, gate):
        def handle(msg, kvs, server):
            if gate is not None:
                gate.wait(5)
            server.response(msg)
            if double:
                server.response(msg)
        return handle

    srv_a = KVServer(0, 0, offices[str(sa)], make_handle(True, None))
    srv_b = KVServer(0, 0, offices[str(sb)], make_handle(False, b_release))
    w = topo.workers(0)[0]
    kw = KVWorker(0, 1, offices[str(w)], [sa, sb], split_range(2))
    done = threading.Event()
    ts = kw.zpush(KVPairs(np.array([0, (1 << 62) - 1]),
                          np.ones(4, np.float32), np.array([2, 2])),
                  on_complete=done.set)
    time.sleep(0.4)  # A answered twice by now
    assert not done.is_set(), "duplicate ACK completed the request early"
    b_release.set()
    kw.wait(ts)
    kw.stop(); srv_a.stop(); srv_b.stop()
    for po in offices.values():
        po.stop()
    fabric.shutdown()


def test_training_survives_drops_with_retry_exact():
    """Message drops anywhere in the fabric + replay dedup must yield the
    EXACT same result as a loss-free run (dedup means drops change
    timing, never arithmetic)."""
    from geomx_tpu.transport.van import FaultPolicy

    topo = Topology(num_parties=2, workers_per_party=1)
    cfg = Config(topology=topo, request_retry_s=0.3)
    sim = Simulation(cfg, fault=FaultPolicy(drop_rate=0.15, seed=3))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        for w in ws:
            w.push(0, np.ones(64, np.float32))
        got = {}
        for i, w in enumerate(ws):
            w.pull(0, lambda t, v, i=i: got.__setitem__(i, np.array(v)))
        for w in ws:
            w.wait_all()
        # global grad = mean over parties of ones → sgd lr=1 → -1 exactly
        for i in range(2):
            np.testing.assert_allclose(got[i], -np.ones(64, np.float32))
    finally:
        sim.shutdown()


def test_auto_checkpoint_written_and_resumable(tmp_path):
    cfg = Config(topology=Topology(num_parties=1, workers_per_party=1),
                 checkpoint_dir=str(tmp_path), auto_ckpt_updates=1)
    sim = Simulation(cfg)
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(32, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 1.0})
        w.push(0, np.ones(32, np.float32))
        w.pull_sync(0)
        path = tmp_path / "global_server_0.npz"
        deadline = time.monotonic() + 5
        # the write is async; poll for a checkpoint that includes the update
        from geomx_tpu.kvstore.checkpoint import load_server_state

        store = {}
        while time.monotonic() < deadline:
            if path.exists():
                try:
                    store, _, _ = load_server_state(str(path))
                except Exception:
                    store = {}
                if 0 in store and np.allclose(store[0], -1.0):
                    break
            time.sleep(0.05)
        np.testing.assert_allclose(store[0], -np.ones(32, np.float32))
    finally:
        sim.shutdown()


def test_tcp_fabric_update_address():
    """update_address re-points a peer and resets connection state."""
    from geomx_tpu.transport.tcp import TcpFabric

    plan = {"a": ("127.0.0.1", 1), "b": ("127.0.0.1", 2)}
    fab = TcpFabric(dict(plan))
    fab._established.add("b")
    fab._dial_window["b"] = 123.0
    fab.update_address("b", ("127.0.0.1", 99))
    assert fab.plan["b"] == ("127.0.0.1", 99)
    assert "b" not in fab._established and "b" not in fab._dial_window
    fab.update_address("nobody", ("x", 1))  # unknown: ignored
    fab.shutdown()


@pytest.mark.slow
def test_global_server_replacement_at_new_address(tmp_path):
    """Kill the global server and bring its REPLACEMENT up at a
    different port (--advertise): the address broadcast re-points every
    peer's fabric and training completes (the reference's re-registration
    broadcast, van.cc:176-193 — whose global tier is a TODO there)."""
    topo = Topology(num_parties=1, workers_per_party=1)
    import tests.test_tcp as ttcp

    base = ttcp.free_base_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "JAX_PLATFORM_NAME": "cpu",
        "GEOMX_CHECKPOINT_DIR": str(tmp_path),
        "GEOMX_AUTO_CKPT_UPDATES": "1",
        "GEOMX_REQUEST_RETRY_S": "1.0",
    })
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(role, extra=()):
        return subprocess.Popen(
            [sys.executable, "-m", "geomx_tpu.launch", "--role", role,
             "--parties", "1", "--workers", "1",
             "--base-port", str(base), "--steps", "25", *extra],
            cwd=cwd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    roles = [str(n) for n in topo.all_nodes()]
    gs_role = str(topo.global_servers()[0])
    procs = {r: spawn(r) for r in roles}
    try:
        ckpt = tmp_path / "global_server_0.npz"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not ckpt.exists():
            time.sleep(0.1)
        assert ckpt.exists(), "no auto-checkpoint appeared"
        time.sleep(1.0)

        procs[gs_role].send_signal(signal.SIGKILL)
        procs[gs_role].wait(timeout=10)
        new_port = ttcp.free_base_port()
        procs[gs_role] = spawn(
            gs_role, extra=["--advertise", f"127.0.0.1:{new_port}"])

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        worker_out = outputs[str(topo.workers(0)[0])]
        assert "steps=25" in worker_out, worker_out[-2000:]
        for r, p in procs.items():
            assert p.returncode == 0, f"{r} rc={p.returncode}: {outputs[r][-800:]}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()


@pytest.mark.slow
def test_global_server_crash_restart_midtraining_resumes_checkpoint(tmp_path):
    """Full multiprocess topology over TCP: SIGKILL the global server
    mid-training, relaunch it, and the workers still finish all steps
    (retry replays the in-flight round; the restart resumes from the
    auto-checkpoint)."""
    topo = Topology(num_parties=1, workers_per_party=1)
    import tests.test_tcp as ttcp

    base = ttcp.free_base_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "JAX_PLATFORM_NAME": "cpu",
        "GEOMX_CHECKPOINT_DIR": str(tmp_path),
        "GEOMX_AUTO_CKPT_UPDATES": "1",
        "GEOMX_REQUEST_RETRY_S": "1.0",
    })
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(role, steps=25):
        return subprocess.Popen(
            [sys.executable, "-m", "geomx_tpu.launch", "--role", role,
             "--parties", "1", "--workers", "1",
             "--base-port", str(base), "--steps", str(steps)],
            cwd=cwd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    roles = [str(n) for n in topo.all_nodes()]
    gs_role = str(topo.global_servers()[0])
    procs = {r: spawn(r) for r in roles}
    try:
        # wait for training to produce at least one checkpointed update
        ckpt = tmp_path / "global_server_0.npz"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not ckpt.exists():
            time.sleep(0.1)
        assert ckpt.exists(), "no auto-checkpoint appeared"
        time.sleep(1.0)  # let a round or two land

        procs[gs_role].send_signal(signal.SIGKILL)
        procs[gs_role].wait(timeout=10)
        time.sleep(1.0)  # cluster runs headless against a dead tier-2
        procs[gs_role] = spawn(gs_role)

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        worker_out = outputs[str(topo.workers(0)[0])]
        assert "steps=25" in worker_out, worker_out[-2000:]
        # the mechanism, not just the outcome: the relaunched tier-2
        # process must have restored from the auto-checkpoint
        assert "resumed from" in outputs[gs_role], outputs[gs_role][-2000:]
        for r, p in procs.items():
            assert p.returncode == 0, f"{r} rc={p.returncode}: {outputs[r][-800:]}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
