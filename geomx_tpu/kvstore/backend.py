"""Pluggable merge backends for the server aggregation lanes.

Both server tiers accumulate gradient pushes per key on their
``ShardExecutor`` lanes (kvstore/common.py).  The MERGE itself —
first-push accumulator seeding, ``acc += v``, the weighted mean at
round close — is delegated to a :class:`MergeBackend` so the same lane
machinery can run host-side (numpy + the native threaded axpy, the
default and the semantic reference) or on an accelerator
(:mod:`geomx_tpu.kvstore.jax_backend`: staged H2D + jitted
donated-argument accumulate, ``shard_map`` + ``psum`` party aggregation
over a device mesh).

Contract every backend honors:

- **dtype promotion**: the accumulator is float32 whatever the push
  payload dtype (f16 pushes promote on the first touch — the same rule
  ``_adopt_or_copy`` always enforced).
- **donated-buffer adopt**: a push whose ``Message.donated`` flag
  transfers ownership may be adopted as the accumulator without a copy
  (numpy path) or consumed by the single staged H2D copy (jax path);
  a NON-donated payload is never aliased or mutated.
- **opaque accumulator**: ``_KeyState.accum`` holds whatever
  :meth:`MergeBackend.seed` returned; the only operations the servers
  apply to it are the backend's own methods plus ``.nbytes`` (memory
  accounting).  Paths that need a host array (optimizer update, WAN
  pack, row-sparse scatter) call :meth:`MergeBackend.materialize`.

``NumpyBackend`` is extracted verbatim from the pre-backend server hot
loop and stays the default: with it, every merge is bit-identical to
HEAD and the ``deterministic`` suite is unaffected (deterministic mode
FORCES numpy — device dispatch order is not replayable).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

import numpy as np

from geomx_tpu.native.bindings import accumulate as _native_accumulate


def _adopt_or_copy(v: np.ndarray, donated: bool) -> np.ndarray:
    """First-push accumulator seed: adopt the wire buffer when the sender
    transferred ownership (``Message.donated``) and it is mutable;
    otherwise take the defensive copy — in-proc delivery is by reference,
    so a non-donated payload may alias the sender's live data, and a
    frozen payload is an immutability promise to OTHER aliases."""
    acc = np.ascontiguousarray(v, dtype=np.float32)
    if donated and acc.flags.writeable:
        return acc
    if np.may_share_memory(acc, v):
        acc = acc.copy()  # never alias (or mutate) the wire buffer
    return acc


class MergeBackend:
    """One server's merge engine (one instance per server; its methods
    run concurrently from that server's merge lanes, each key confined
    to one lane).

    ``max_lanes`` caps the server's lane count when the backend cannot
    merge more streams in parallel than that (a single device stream
    serializes dispatch; extra lanes only add contention) — ``None``
    leaves :func:`geomx_tpu.kvstore.common.resolve_server_shards`
    alone."""

    name = "abstract"
    max_lanes: Optional[int] = None

    def seed(self, v: np.ndarray, donated: bool, key: Optional[int] = None):
        """First push of a round: build and return the accumulator
        (f32-promoted; adopt ``v`` only under the donation contract).
        ``key`` is the ps-key the round belongs to — backends that keep
        cross-round per-key state (the quantized rung's error-feedback
        residual) key it here; the numpy path ignores it."""
        raise NotImplementedError

    def accumulate(self, acc, v: np.ndarray):
        """Merge one push into the accumulator; returns the (possibly
        replaced) accumulator handle."""
        raise NotImplementedError

    def scale(self, acc, s: float):
        """In-place weighted mean at round close (the HFA convex
        renormalization); returns the accumulator handle."""
        raise NotImplementedError

    def materialize(self, acc) -> np.ndarray:
        """The accumulator as a host f32 ndarray the server owns (the
        identity on the numpy path — NO copy; a device sync + one D2H
        on an accelerator path)."""
        raise NotImplementedError

    def stats(self) -> dict:
        """Observability: merged into the server's QUERY_STATS body."""
        return {"merge_backend": self.name}

    def screen_finite(self, v: np.ndarray, mag_max: float = 0.0) -> bool:
        """Gradient-hygiene screen (Config.integrity_push_screen): True
        iff every element of the push payload is finite — and, when
        ``mag_max`` > 0, within ``[-mag_max, mag_max]``.  The host
        reference is one fused pass; accelerator backends override with
        a jitted device reduction so the screen ships one scalar back
        instead of the tensor."""
        if mag_max > 0.0:
            with np.errstate(invalid="ignore"):
                return bool((np.abs(v) <= mag_max).all())
        return bool(np.isfinite(v).all())

    def make_device_optimizer(self, spec: dict):
        """Optimizer stage of the round close: return a device-resident
        optimizer for ``spec`` (a ``make_optimizer`` config dict), or
        None when this backend keeps the optimizer on the host (the
        numpy path always does; the jax path returns one for the
        supported family when ``merge_opt_device`` is on).  The server
        treats a non-None return as "this backend closes rounds without
        materializing": weights + moments stay device-resident and host
        copies happen only at serve/checkpoint/handoff events (see
        :class:`geomx_tpu.kvstore.jax_backend.DeviceOptimizer` for the
        full contract, including ``export_state``/``import_state`` —
        the hooks every snapshot path goes through so the trajectory
        survives failover and reassignment)."""
        return None

    def make_codec_stage(self, config):
        """Codec stage of the WAN path: return a device-resident codec
        engine for ``config`` (push-compression + decode kernels), or
        None when this backend keeps the codecs on the host (the numpy
        path always does; the jax path returns one when
        ``codec_device`` resolves on — see
        :func:`resolve_codec_device`).  The servers treat a non-None
        return as "encode may read the device accumulator directly and
        decode may land device arrays": the encode side materializes
        only the wire-ready compressed payload, the decode side feeds
        ``seed``/``accumulate`` a device array the backend recognizes
        without re-staging.  Wire frames are bit-identical to the
        :mod:`geomx_tpu.compression.codecs` reference in both
        directions (cross-decode parity is part of the contract)."""
        return None

    def stop(self) -> None:  # release device handles, if any
        pass


def _accumulate_kernel():
    """The threaded host accumulate, resolved late through the server
    module when it is loaded: ``tests/test_sharded_merge`` wedges a
    lane by rebinding ``kvstore.server._native_accumulate``, and that
    published patch point must keep working now the call site lives
    here."""
    srv = sys.modules.get("geomx_tpu.kvstore.server")
    if srv is not None:
        return srv._native_accumulate
    return _native_accumulate


class NumpyBackend(MergeBackend):
    """The host merge path, verbatim from the pre-backend server hot
    loop: adopt-or-copy seed, native threaded axpy accumulate (numpy
    fallback inside the binding), ``np.multiply(..., out=)`` scale.
    Bit-identical to HEAD by construction — zero-copy recv views flow
    straight into the accumulator, no host copy is added anywhere."""

    name = "numpy"

    def __init__(self, config=None):
        self._threads = int(getattr(config, "server_merge_threads", 0)
                            or 0)

    def seed(self, v: np.ndarray, donated: bool,
             key: Optional[int] = None) -> np.ndarray:
        return _adopt_or_copy(v, donated)

    def accumulate(self, acc: np.ndarray, v: np.ndarray) -> np.ndarray:
        # native threaded merge for big tensors (the server hot loop;
        # ref: kvstore_dist_server.h:1277-1296)
        _accumulate_kernel()(acc, np.ascontiguousarray(v, np.float32),
                             self._threads)
        return acc

    def scale(self, acc: np.ndarray, s: float) -> np.ndarray:
        np.multiply(acc, s, out=acc)
        return acc

    def materialize(self, acc) -> np.ndarray:
        return acc  # row-sparse scatters hand host arrays through too


# one probe per process: jax backend-liveness can cost a device query
_probe_mu = threading.Lock()
_accel_live: Optional[bool] = None


def _accelerator_live() -> bool:
    """True iff importing jax would land on a non-CPU backend.  Fast
    False (no jax import) when the platform env pins cpu — the tier-1
    / CI posture — so ``auto`` never pays backend-init latency on a
    host that provably has no accelerator."""
    global _accel_live
    for var in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME"):
        val = os.environ.get(var, "")
        if val and all(p.strip().lower() == "cpu"
                       for p in val.split(",") if p.strip()):
            return False
    with _probe_mu:
        if _accel_live is None:
            try:
                import jax

                _accel_live = jax.default_backend() != "cpu"
            except Exception:
                _accel_live = False
        return _accel_live


def resolve_merge_backend(config) -> str:
    """The effective backend name for a server: ``Config.merge_backend``
    (``auto`` | ``numpy`` | ``jax``), with ``GEOMX_MERGE_BACKEND`` as
    the env fallback for directly-constructed Configs (the way
    GEOMX_SERVER_SHARDS shakes the striped-merge path, so a whole test
    suite runs under the jax lanes without threading the knob through
    every fixture).  Rules:

    - ``deterministic`` FORCES numpy — device dispatch completion order
      is not replayable run-to-run.
    - ``auto`` picks jax iff an accelerator backend is live (TPU/GPU);
      plain CPU hosts keep the numpy reference path.
    - an explicit ``jax`` on a host whose jax cannot import degrades to
      numpy loudly at construction (:func:`make_merge_backend`)."""
    if getattr(config, "deterministic", False):
        return "numpy"
    choice = (getattr(config, "merge_backend", "") or "").strip().lower()
    if choice in ("", "auto"):
        env = os.environ.get("GEOMX_MERGE_BACKEND", "").strip().lower()
        choice = env or "auto"
    if choice == "numpy":
        return "numpy"
    if choice == "jax":
        return "jax"
    if choice != "auto":
        raise ValueError(
            f"unknown merge_backend {choice!r} (auto|numpy|jax)")
    return "jax" if _accelerator_live() else "numpy"


def resolve_opt_device(config) -> bool:
    """Whether the jax backend should run the device-resident optimizer
    stage: ``Config.merge_opt_device`` (default on), with
    ``GEOMX_MERGE_OPT_DEVICE`` honored as the env override for
    directly-constructed Configs (so a whole suite can pin the stage
    off the way GEOMX_MERGE_BACKEND pins the lanes on).  Irrelevant
    under the numpy backend — the host optimizer is the only stage."""
    if not bool(getattr(config, "merge_opt_device", True)):
        return False
    env = os.environ.get("GEOMX_MERGE_OPT_DEVICE", "").strip().lower()
    if env:
        return env not in ("0", "false", "no", "off")
    return True


def resolve_codec_device(config) -> bool:
    """Whether the jax backend should run the device-resident WAN codec
    stage: ``Config.codec_device`` (default on), with
    ``GEOMX_CODEC_DEVICE`` honored as the env override for
    directly-constructed Configs (same fallback idiom as
    GEOMX_MERGE_OPT_DEVICE).  Deterministic mode forces the host
    codecs — they are the bit-compat reference and their dispatch is
    replayable.  Irrelevant under the numpy backend, which has no
    device to encode on."""
    if getattr(config, "deterministic", False):
        return False
    if not bool(getattr(config, "codec_device", True)):
        return False
    env = os.environ.get("GEOMX_CODEC_DEVICE", "").strip().lower()
    if env:
        return env not in ("0", "false", "no", "off")
    return True


def make_merge_backend(config, node: str = "?") -> MergeBackend:
    """Construct the resolved backend; an explicit-jax host whose jax
    stack cannot build one degrades to numpy with a printed reason
    instead of taking the server down (the merge must never be the
    component that can't boot)."""
    kind = resolve_merge_backend(config)
    if kind == "jax":
        try:
            from geomx_tpu.kvstore.jax_backend import JaxBackend

            return JaxBackend(config)
        except Exception as e:  # missing/broken jax: gate, don't crash
            print(f"[{node}] merge backend 'jax' unavailable "
                  f"({type(e).__name__}: {e}); falling back to numpy")
    return NumpyBackend(config)
