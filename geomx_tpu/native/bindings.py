"""ctypes bindings for the native codec library.

Build is on-demand: first import compiles ``libgeocodecs.so`` with the
Makefile (g++; pybind11 isn't available in this environment, so the C ABI
+ ctypes is the binding layer).  If no toolchain is present the import
degrades gracefully — ``available() == False`` and callers fall back to
the numpy implementations, which remain the semantic reference.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libgeocodecs.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_i64 = ctypes.c_int64
_f32 = ctypes.c_float


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "-C", _DIR, "libgeocodecs.so"],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_SO)
    except (OSError, subprocess.SubprocessError):
        return False


def _stale() -> bool:
    """True when any source is newer than the built library (a rebuilt
    tree with an old .so would otherwise miss newly added symbols)."""
    try:
        so_mtime = os.path.getmtime(_SO)
    except OSError:
        return True
    for f in os.listdir(_DIR):
        if f.endswith(".cc") and os.path.getmtime(os.path.join(_DIR, f)) > so_mtime:
            return True
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if _stale() and not _build() and not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.geo_pack2bit.argtypes = [_f32p, _f32p, _u8p, _i64, _f32]
        lib.geo_unpack2bit.argtypes = [_u8p, _f32p, _i64, _f32]
        lib.geo_dgc_update.argtypes = [_f32p, _f32p, _f32p, _i64, _f32]
        lib.geo_topk_abs.argtypes = [_f32p, _i64, _i64, _i64p]
        lib.geo_topk_abs.restype = _i64
        lib.geo_select_threshold.argtypes = [_f32p, _i64, _f32, _i64, _i64p]
        lib.geo_select_threshold.restype = _i64
        lib.geo_sparse_add.argtypes = [_f32p, _f32p, _i64p, _i64]
        # newer symbols may be absent from a stale .so we couldn't rebuild
        # (no toolchain); callers probe with hasattr so the codec symbols
        # above keep accelerating either way
        if hasattr(lib, "geo_recordio_index"):
            lib.geo_recordio_index.argtypes = [_u8p, _i64, _i64, _i64p, _i64p]
            lib.geo_recordio_index.restype = _i64
        if hasattr(lib, "geo_axpy_acc"):
            lib.geo_axpy_acc.argtypes = [_f32p, _f32p, _i64, ctypes.c_int]
        _lib = lib
        return _lib


def lib() -> Optional[ctypes.CDLL]:
    return _load()


def available() -> bool:
    return _load() is not None


def _usable_cores() -> int:
    """Cores this PROCESS may run on — ``os.cpu_count()`` reports the
    host's cores even inside a cpuset/container pinned to one, which is
    exactly how the r4 bench host ended up spawning cpu_count threads
    on a single core (0.34 GB/s native vs 0.63 numpy, VERDICT r4
    weak 7)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


_axpy_wins: dict = {}  # thread count -> calibration verdict
_calib_lock = threading.Lock()


def _force_accum() -> str:
    """The GEOMX_FORCE_ACCUM override: "native" / "numpy" / "" (auto).
    Read per call so tests and operators can flip it at runtime; the
    documented surface is docs/env-vars.md."""
    return os.environ.get("GEOMX_FORCE_ACCUM", "").strip().lower()


def _axpy_beats_numpy(l, threads: int) -> bool:
    """One-shot-per-thread-count calibration: time the native threaded
    axpy against numpy's add on a representative slab and cache the
    verdict.  The kernel is pure memory bandwidth, so whichever wins
    here wins at every large size; auto-disabling when numpy wins
    guarantees the native path is never a pessimization on a host we
    didn't tune for (VERDICT r4: native_axpy >= server_merged or
    auto-disabled).  Keyed on ``threads`` — a 2-thread caller and a
    16-thread caller can legitimately get different verdicts."""
    won = _axpy_wins.get(threads)
    if won is None:
        import time
        with _calib_lock:
            won = _axpy_wins.get(threads)
            if won is not None:
                return won
            n = 1 << 22  # 16 MB slabs: past every cache, quick to run
            a = np.ones(n, np.float32)
            b = np.ones(n, np.float32)
            t_nat = t_np = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                l.geo_axpy_acc(a, b, n, threads)
                t_nat = min(t_nat, time.perf_counter() - t0)
                t0 = time.perf_counter()
                a += b
                t_np = min(t_np, time.perf_counter() - t0)
            won = _axpy_wins[threads] = t_nat < t_np
    return won


def _clamped_threads(threads: int) -> int:
    cores = _usable_cores()
    return cores if threads <= 0 else min(threads, cores)


def calibrate(threads: int = 0) -> str:
    """Run (or fetch) the axpy-vs-numpy calibration for this thread
    count NOW, returning the winning backend name.  Servers call this
    at startup — the locked merge path must never pay the ~2x16 MB
    timing run (advisor r5); ``accumulate`` only consults the cached
    verdict."""
    forced = _force_accum()
    if forced in ("native", "numpy"):
        return forced
    l = _load()
    if l is None or not hasattr(l, "geo_axpy_acc"):
        return "numpy"
    t = _clamped_threads(threads)
    if t <= 1:
        return "numpy"
    return "native" if _axpy_beats_numpy(l, t) else "numpy"


def calibrate_async(threads: int = 0) -> None:
    """Warm the calibration cache on a daemon thread (eager server
    startup).  Idempotent and cheap once the verdict is cached."""
    threading.Thread(target=calibrate, args=(threads,),
                     daemon=True, name="axpy-calibrate").start()


def axpy_backend(threads: int = 0) -> str:
    """Which implementation ``accumulate`` would use for a large slab on
    this host right now: "native" or "numpy" (observability for the
    bench; runs the calibration if it hasn't happened yet)."""
    return calibrate(threads)


def accumulate(acc: np.ndarray, v: np.ndarray, threads: int = 0) -> None:
    """acc += v with the native threaded kernel when it wins (the
    server merge hot loop; ref: engine-pool-scheduled merge,
    kvstore_dist_server.h:1277-1296).  ``threads`` 0 = one per usable
    core (affinity-aware), always clamped to the affinity mask.  Falls
    back to numpy without the library, on small slabs (thread spawn
    dominates), on single-core hosts, and on hosts where the one-shot
    calibration shows numpy's add is faster.  ``GEOMX_FORCE_ACCUM``
    (native|numpy) overrides the choice outright.

    NEVER calibrates here: this runs under the server's state lock
    (advisor r5) — an uncalibrated thread count falls back to numpy for
    this call and schedules the calibration in the background (servers
    normally pre-warm it via ``calibrate_async`` at startup)."""
    forced = _force_accum()
    l = _load()
    native_ok = (l is not None and hasattr(l, "geo_axpy_acc")
                 and acc.dtype == np.float32 and v.dtype == np.float32
                 and len(acc) == len(v)
                 and acc.flags.c_contiguous and v.flags.c_contiguous)
    if forced == "numpy" or not native_ok:
        acc += v
        return
    t = _clamped_threads(threads)
    if forced == "native":
        l.geo_axpy_acc(acc, v, len(acc), max(t, 1))
        return
    if len(acc) >= (1 << 20) and t > 1:
        won = _axpy_wins.get(t)
        if won is None:
            # not calibrated yet — do NOT time it under the caller's
            # lock; numpy this round, background-calibrate for the next
            calibrate_async(t)
        elif won:
            l.geo_axpy_acc(acc, v, len(acc), t)
            return
    acc += v
