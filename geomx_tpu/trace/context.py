"""Trace context: the (trace_id, span_id) pair that rides every message.

The propagation model mirrors Dapper/OpenTelemetry trimmed to what the
HiPS tree needs (cf. the cross-host timeline the TensorFlow system paper
treats as prerequisite to optimizing its distributed runtime —
PAPERS.md):

- a **trace** is one sampled synchronization round; every worker derives
  the same ``trace_id`` from the round index, so the collector can merge
  all parties' spans of round N into one tree without coordination;
- a **span** is one timed region on one node (worker push issue, local
  merge, optimizer step, ...); its id is process-unique;
- the context travels (a) between threads of one node implicitly — a
  thread-local installed by the span that is currently open — and
  (b) between nodes explicitly as ``Message.trace_id`` /
  ``Message.span_id`` / ``Message.parent_span_id`` / ``Message.sampled``,
  stamped by ``Van.send`` from the sender's thread-local and re-installed
  around the receiver's handler by ``Customer``.

Overhead discipline: the whole subsystem hides behind the module-global
``ACTIVE`` flag (set once, when a role is constructed with
``Config.trace_sample_every > 0``).  Every hook on the message hot path
checks that single attribute before doing anything else, and the span
factory returns a shared no-op object when tracing is off or the current
round is unsampled — the disabled path allocates nothing per message.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Optional

# Single gate for every hot-path hook.  Flipped (never cleared) by
# activate(); reading one module attribute is the entire disabled cost.
ACTIVE = False

_tls = threading.local()

# span ids: process-unique, nonzero.  High bits are a per-process salt so
# two OS processes of one deployment cannot collide; low bits count.
# Salt is capped at 30 bits so salt<<32 | counter always fits the wire's
# SIGNED int64 header field (struct "q").
_SALT = ((int.from_bytes(os.urandom(4), "little") & 0x3FFFFFFF) | 1) << 32
_ids = itertools.count(1)


def activate() -> None:
    global ACTIVE
    ACTIVE = True


def new_span_id() -> int:
    return _SALT | next(_ids)


def trace_id_for_round(round_idx: int) -> int:
    """Deterministic nonzero trace id shared by every node for one
    sampled round — the cross-party merge key."""
    return int(round_idx) + 1


class TraceContext:
    """Immutable-by-convention (trace_id, span_id) the current thread is
    working under.  ``span_id`` is the id new child spans and outbound
    messages use as their parent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id


def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def swap(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as the thread's context; returns the previous one
    (restore() it when the scope ends)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


def restore(prev: Optional[TraceContext]) -> None:
    _tls.ctx = prev


class suppressed:
    """Scope with NO trace context — used around the tracer's own
    report shipping so trace traffic never traces itself."""

    def __enter__(self):
        self._prev = swap(None)
        return self

    def __exit__(self, *exc):
        restore(self._prev)
        return False
