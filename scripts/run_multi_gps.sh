#!/usr/bin/env bash
# Acceptance config: multi_gps (mirrors the reference scripts/cpu/run_multi_gps.sh)
GSERVERS="${GSERVERS:-2}" exec "$(dirname "$0")/run_cluster.sh" 
