"""Read-path client for the replica tier.

Unlike :class:`~geomx_tpu.kvstore.client.WorkerKVStore` (which slices
requests across the training tiers), a :class:`ReplicaClient` talks to
ONE replica that holds the whole key space, and it needs the response
*body* (the ``{staleness_s, version, rounds_at_refresh}`` contract
metadata), so it processes raw messages instead of riding KVWorker's
merge path.  An inference frontend holds one client per replica and
load-balances/fails over by retargeting.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from geomx_tpu.core.config import Config, NodeId, Role
from geomx_tpu.kvstore.common import APP_PS, Cmd, Ctrl
from geomx_tpu.kvstore.keys import KeyPlan
from geomx_tpu.ps import KVPairs, Postoffice
from geomx_tpu.ps.kv_app import _App
from geomx_tpu.transport.message import Domain, Message


class ReplicaError(RuntimeError):
    """A replica answered with an error body.  ``body`` keeps the
    STRUCTURED response — the admission-control shed fields
    (``shed``/``retry_after_s``/``inflight``/``retired``) the balancer
    needs to deprioritize the replica and retry elsewhere, which the
    flattened message string cannot carry."""

    def __init__(self, message: str, body: Optional[dict] = None):
        super().__init__(message)
        self.body = dict(body or {})

    @property
    def shed(self) -> bool:
        """True for an explicit admission-control refusal (the replica
        is overloaded or retiring, not broken)."""
        return bool(self.body.get("shed"))

    @property
    def retry_after_s(self) -> float:
        return float(self.body.get("retry_after_s", 0.0) or 0.0)


class ReplicaClient(_App):
    """One query endpoint toward one serve replica."""

    def __init__(self, postoffice: Postoffice,
                 config: Optional[Config] = None,
                 replica: Union[NodeId, int] = 0,
                 customer_id: int = 3,
                 advertise: Optional[tuple] = None):
        # state BEFORE super(): the Customer registers with the
        # postoffice inside _App.__init__, and from that moment
        # _process may run on a delivery thread
        self._mu = threading.Lock()
        self._replies: Dict[int, Message] = {}
        super().__init__(APP_PS, customer_id, postoffice)
        self.po = postoffice
        self.config = config or postoffice.config
        if not isinstance(replica, NodeId):
            replica = NodeId(Role.REPLICA, int(replica))
        self.target = replica
        # OUT-OF-PLAN TCP querier (the serve.load driver, an inference
        # frontend outside the static plan): ship the reply address in
        # every request body, status-console style, so the replica can
        # dial back
        self._advertise = advertise
        # the same deterministic tensor→key encoding every node computes
        self.plan = KeyPlan(
            num_shards=postoffice.topology.num_global_servers,
            bigarray_bound=self.config.bigarray_bound)
        self.reads = 0

    def retarget(self, replica: Union[NodeId, int]):
        """Point at another replica (load balancing / replica death)."""
        if not isinstance(replica, NodeId):
            replica = NodeId(Role.REPLICA, int(replica))
        self.target = replica

    # ---- message plumbing ----------------------------------------------------
    def _process(self, msg: Message):
        if not msg.push and not msg.pull:
            self._handle_command(msg)
            return
        if msg.request:
            return  # stray
        with self._mu:
            self._replies[msg.timestamp] = msg
            while len(self._replies) > 1024:  # timed-out orphans
                self._replies.pop(next(iter(self._replies)))
        self.customer.add_response(msg.timestamp)

    def _body(self, body: Optional[dict] = None) -> Optional[dict]:
        if self._advertise is None:
            return body
        out = dict(body or {})
        out["addr"] = [self._advertise[0], int(self._advertise[1])]
        return out

    def _roundtrip(self, msg_fields: dict, timeout: float) -> Message:
        if self._advertise is not None:
            msg_fields = dict(msg_fields,
                              body=self._body(msg_fields.get("body")))
        ts = self.customer.new_request(1)
        self.po.van.send(Message(
            recipient=self.target, domain=Domain.GLOBAL,
            app_id=self.customer.app_id,
            customer_id=self.customer.customer_id,
            timestamp=ts, request=True, **msg_fields))
        self.customer.wait(ts, timeout=timeout)
        with self._mu:
            msg = self._replies.pop(ts)
        body = msg.body if isinstance(msg.body, dict) else {}
        if "error" in body:
            raise ReplicaError(str(body["error"]), body=body)
        return msg

    # ---- public API ----------------------------------------------------------
    def list_keys(self, timeout: float = 5.0) -> List[int]:
        """The key set the replica currently holds."""
        ts = self.send_cmd(self.target, Ctrl.LIST_KEYS,
                           body=self._body(), domain=Domain.GLOBAL,
                           wait=False)
        self.customer.wait(ts, timeout=timeout)
        reply = self.cmd_response(ts) or {}
        return [int(k) for k in reply.get("keys", ())]

    def stats(self, timeout: float = 5.0) -> dict:
        ts = self.send_cmd(self.target, Ctrl.QUERY_STATS,
                           body=self._body(), domain=Domain.GLOBAL,
                           wait=False)
        self.customer.wait(ts, timeout=timeout)
        return self.cmd_response(ts) or {}

    def pull(self, keys, timeout: float = 10.0) -> Tuple[KVPairs, dict]:
        """Read raw ps keys; returns ``(KVPairs, meta)`` where meta is
        the replica's staleness contract body."""
        ks = np.asarray(sorted(int(k) for k in keys), dtype=np.int64)
        msg = self._roundtrip({"pull": True, "cmd": int(Cmd.SERVE_PULL),
                               "keys": ks}, timeout)
        self.reads += 1
        return (KVPairs(msg.keys, msg.vals, msg.lens),
                dict(msg.body or {}))

    def pull_tensor(self, tid: int, size: int,
                    timeout: float = 10.0) -> Tuple[np.ndarray, dict]:
        """Read one flat tensor by id (reassembled across its keys)."""
        parts = self.plan.parts(tid, size)
        kvs, meta = self.pull([p.ps_key for p in parts], timeout)
        got = {k: v for k, v in kvs.slices()}
        out = np.empty(size, dtype=np.float32)
        for p in parts:
            out[p.start:p.start + p.length] = got[p.ps_key]
        return out, meta

    def predict(self, x: np.ndarray, layers: List[tuple],
                relu: bool = True,
                timeout: float = 10.0) -> Tuple[np.ndarray, dict]:
        """Forward pass on the replica: ``layers`` is a list of
        ``(tensor_id, (rows, cols))`` (optionally ``(tensor_id,
        (rows, cols), bias_tensor_id)``) naming an MLP's weight chain.
        Each layer tensor must live whole under one ps key (like the
        row-sparse contract — partitioned layers are rejected here, not
        corrupted there)."""
        x = np.ascontiguousarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        spec = []
        for ly in layers:
            tid, (rows, cols) = ly[0], ly[1]
            parts = self.plan.parts(tid, rows * cols)
            if len(parts) != 1:
                raise ValueError(
                    f"predict layer tensor {tid} ({rows}x{cols}) spans "
                    f"{len(parts)} ps keys; predict layers must fit one "
                    "key (raise bigarray_bound or shrink the layer)")
            ent = {"key": parts[0].ps_key, "rows": rows, "cols": cols}
            if len(ly) > 2 and ly[2] is not None:
                bparts = self.plan.parts(int(ly[2]), cols)
                if len(bparts) != 1:
                    raise ValueError(f"bias tensor {ly[2]} spans keys")
                ent["bias"] = bparts[0].ps_key
            spec.append(ent)
        msg = self._roundtrip({
            "push": True, "pull": True, "cmd": int(Cmd.PREDICT),
            "keys": np.array([0], dtype=np.int64),
            "vals": x.ravel(),
            "lens": np.array([x.size], dtype=np.int64),
            "body": {"layers": spec, "batch": int(x.shape[0]),
                     "relu": bool(relu)},
        }, timeout)
        body = dict(msg.body or {})
        shape = body.get("shape") or [int(x.shape[0]), -1]
        self.reads += 1
        return np.asarray(msg.vals, np.float32).reshape(shape), body
