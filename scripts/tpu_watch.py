#!/usr/bin/env python
"""Tunnel watcher: keep trying to capture on-chip bench numbers.

The axon TPU tunnel dies for whole rounds at a time (BENCH r1-r3 all
lost their on-chip numbers to it).  This watcher loops for the lifetime
of a build session, probing the tunnel every ``--interval`` seconds; the
moment a probe succeeds it runs every TPU bench child via
``bench.py --capture-lkg``, which persists each result to
``TPU_LKG.json``.  ``bench.py`` merges that cache (with staleness
markers) into its record whenever its own live probe fails — so ONE
live-tunnel window anywhere in a round is enough to land the round's
on-chip record (VERDICT r3 item 1).

Run it detached at session start:

    nohup python scripts/tpu_watch.py --interval 600 \
        >> tpu_watch.log 2>&1 &

Stops by itself once every TPU child has a fresh capture (< --max-age
old), or runs until killed with --forever.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))
from bench import TPU_CHILDREN as CHILDREN  # noqa: E402 — single source
from bench import TPU_LKG_PATH as LKG      # noqa: E402


def fresh_captures(max_age_s: float) -> set:
    try:
        cur = json.loads(LKG.read_text())
    except (OSError, json.JSONDecodeError):
        return set()
    now = time.time()
    out = set()
    for name, entry in cur.items():
        t = entry.get("captured_unix")
        if t is None:
            # legacy entry without epoch seconds: decode the UTC string
            # with calendar.timegm (time.mktime would apply local DST)
            import calendar
            try:
                t = calendar.timegm(time.strptime(
                    entry.get("captured_at", ""), "%Y-%m-%dT%H:%M:%SZ"))
            except ValueError:
                continue
        if now - t < max_age_s:
            out.add(name)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0,
                    help="seconds between probe attempts")
    ap.add_argument("--max-age", type=float, default=24 * 3600,
                    help="a capture younger than this counts as fresh")
    ap.add_argument("--forever", action="store_true",
                    help="keep refreshing even after a full capture")
    args = ap.parse_args()

    attempt = 0
    while True:
        attempt += 1
        have = fresh_captures(args.max_age)
        missing = [c for c in CHILDREN if c not in have]
        if not missing and not args.forever:
            print(f"[tpu_watch] all children fresh in {LKG.name}; done",
                  flush=True)
            return
        print(f"[tpu_watch] attempt {attempt}: missing={missing}",
              flush=True)
        try:
            subprocess.run(
                [sys.executable, str(ROOT / "bench.py"), "--capture-lkg"],
                timeout=1800, cwd=ROOT, env=dict(os.environ),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
        except (subprocess.SubprocessError, OSError) as e:
            print(f"[tpu_watch] capture pass failed: {e}", flush=True)
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
