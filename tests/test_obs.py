"""Cluster telemetry plane (ISSUE 7 tentpole): time-series metrics
shipping, the SLO health engine, and the live cluster-state console.

Covers: pump → collector e2e over the wire (ring series, boot-fenced
rates, Prometheus exposition, perfetto counter tracks in the merged
trace), the acceptance failover-visibility scenario (kill one global
shard's primary → ``cluster_state()`` flips the holder + term within a
collection interval, the health engine emits exactly one round-stall
alert for that shard followed by a recovery record), the disabled-path
guard (default config: no pump, no threads, no METRICS_REPORT frames on
a wire tap), the Ctrl.CLUSTER_STATE wire query, health-rule units over
synthetic series, QUERY_STATS uptime/boot, the NaN gauge fence, and the
registry reset fixture.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.kvstore.common import Ctrl
from geomx_tpu.kvstore.keys import encode_tensor
from geomx_tpu.transport.message import Domain
from geomx_tpu.utils.metrics import (reset_system_metrics, system_counter,
                                     system_gauge, system_snapshot)


def _obs_cfg(parties=2, workers=1, **kw):
    kw.setdefault("enable_obs", True)
    kw.setdefault("obs_interval_s", 0.0)  # manual pump/tick
    return Config(topology=Topology(num_parties=parties,
                                    workers_per_party=workers), **kw)


def _wait_for(pred, timeout=15.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _run_rounds(sim, rounds, tids=(0,), n=32):
    ws = sim.all_workers()
    for _ in range(rounds):
        for w in ws:
            for t in tids:
                w.push(t, np.ones(n, np.float32))
        for w in ws:
            for t in tids:
                w.pull_sync(t)
            w.wait_all()


# ---------------------------------------------------------------------------
# pump -> collector e2e
# ---------------------------------------------------------------------------

def test_pump_collector_e2e_series_and_rates():
    """Every node's samples land in the collector's rings over the
    METRICS_REPORT wire path; stats carry the servers' QUERY_STATS dict
    and rates are computable from consecutive samples."""
    sim = Simulation(_obs_cfg())
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(32, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 2)
        sim.pump_metrics()
        mc = sim.metrics_collector
        # every role reported (workers, both tiers, schedulers)
        nodes = set(mc.nodes())
        assert {"worker:0@p0", "server:0@p0", "global_server:0",
                "global_scheduler:0"} <= nodes
        # server stats ARE the QUERY_STATS body
        assert mc.value("server:0@p0", "wan_push_rounds") == 2
        assert mc.value("global_server:0", "key_rounds") == 2
        _run_rounds(sim, 2)
        sim.pump_metrics()
        assert mc.value("global_server:0", "key_rounds") == 4
        r = mc.rate("server:0@p0", "wan_send_bytes")
        assert r is not None and r > 0
        # series are bounded rings
        for _ in range(12):
            sim.pump_metrics()
        assert len(mc.series("worker:0@p0", "send_bytes")) \
            <= sim.config.obs_window
    finally:
        sim.shutdown()


def test_prometheus_exposition_and_nan_fence():
    """The text exposition lists every reported family with a node
    label, and a never-set gauge (NaN) can never reach it — nor any
    shipped sample (JSON-invalid NaN is fenced at the pump)."""
    sim = Simulation(_obs_cfg(parties=1))
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 1, n=8)
        # a never-set gauge on a pumped node's prefix
        system_gauge("server:0@p0.test_unset_gauge")
        system_gauge("server:0@p0.test_set_gauge").set(1.5)
        sim.pump_metrics()
        pump = sim.metrics_pumps["server:0@p0"]
        body = pump.sample()
        json.dumps(body, allow_nan=False)  # raises on NaN leakage
        assert "server:0@p0.test_unset_gauge" not in body["metrics"]
        assert body["metrics"]["server:0@p0.test_set_gauge"] == 1.5
        sim.pump_metrics()
        txt = sim.metrics_collector.prometheus_text()
        assert 'geomx_test_set_gauge{node="server:0@p0"} 1.5' in txt
        assert "test_unset_gauge" not in txt
        assert 'geomx_key_rounds{node="global_server:0"}' in txt
        assert "NaN" not in txt and "nan" not in txt.lower().replace(
            "instance", "")
        # snapshot-level fence for direct registry readers
        snap = system_snapshot(skip_unset=True)
        assert "server:0@p0.test_unset_gauge" not in snap
        assert "server:0@p0.test_unset_gauge" in system_snapshot()
    finally:
        sim.shutdown()


def test_counter_tracks_merge_into_trace_json(tmp_path):
    """With tracing AND telemetry on, the merged trace JSON carries
    perfetto counter-track ("ph": "C") events from the collected series
    next to the round spans, on the same rebased timeline."""
    sim = Simulation(_obs_cfg(trace_sample_every=1))
    try:
        ws = sim.all_workers()
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        for w in ws:
            w.init(0, np.zeros(32, np.float32))
        for r in range(2):
            for w in ws:
                with w.trace_round(r):
                    w.push(0, np.ones(32, np.float32))
                    w.pull(0, lambda t, a: None)
            for w in ws:
                w.wait_all()
        sim.pump_metrics()
        trace = sim.dump_trace(str(tmp_path / "t.json"))
        evs = trace["traceEvents"]
        counters = [e for e in evs if e.get("ph") == "C"]
        spans = [e for e in evs if e.get("ph") == "X"]
        assert counters and spans, (len(counters), len(spans))
        names = {e["name"] for e in counters}
        assert "metric.key_rounds" in names
        assert "metric.wan_send_bytes" in names
        # same rebased timeline: counter timestamps sit inside the
        # span timeline's range (all ts >= 0 after rebase)
        assert all(e["ts"] >= 0 for e in counters)
        with open(tmp_path / "t.json") as f:
            json.load(f)  # the dump stays valid JSON
    finally:
        sim.shutdown()


def test_cluster_state_wire_query():
    """Ctrl.CLUSTER_STATE answered over the wire: a worker-side command
    round trip returns the same merged state Simulation.cluster_state()
    composes."""
    sim = Simulation(_obs_cfg(parties=1))
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 1, n=8)
        sim.pump_metrics()
        kv = sim.worker(0, 0)
        ts = kv.worker.send_cmd(sim.topology.global_scheduler(),
                                Ctrl.CLUSTER_STATE, domain=Domain.GLOBAL,
                                wait=False)
        kv.worker.customer.wait(ts, timeout=10.0)
        state = kv.worker.cmd_response(ts)
        assert isinstance(state, dict)
        shards = {int(k): v for k, v in state["shards"].items()}
        assert shards[0]["holder"] == "global_server:0"
        assert state["topology"]["num_parties"] == 1
        assert state["telemetry"]["reports"] > 0
        # renders without blowing up, naming the holder
        from geomx_tpu.obs import render_text

        txt = render_text(state)
        assert "global_server:0" in txt
        assert sim.state_service.queries_served == 1
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# acceptance: live failover visibility
# ---------------------------------------------------------------------------

@pytest.mark.failover
def test_failover_visible_in_cluster_state_and_round_stall_alert():
    """Acceptance: kill one global shard's primary mid-training —
    cluster_state() reports the promoted holder + bumped term within
    one collection interval, and the health engine emits exactly one
    round-stall alert for that shard followed by a recovery record."""
    cfg = Config(
        topology=Topology(num_parties=2, workers_per_party=1,
                          num_global_servers=2, num_standby_globals=2),
        enable_obs=True, obs_interval_s=0.0,
        request_retry_s=0.4, heartbeat_interval_s=0.05,
        heartbeat_timeout_s=0.4, replicate_every=1, retry_backoff_cap=2,
        obs_stall_min_s=0.3, obs_stall_factor=2.0)
    sim = Simulation(cfg)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(16, np.float32))
            w.init(1, np.zeros(16, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 1.0})
        for _ in range(3):
            # pump + tick per round: the stall rule arms a shard only
            # after OBSERVING its progress (an idle-since-boot shard
            # must never alert), so the series needs per-round samples
            _run_rounds(sim, 1, tids=(0, 1), n=16)
            sim.pump_metrics()
            sim.health.tick()
        st = sim.cluster_state()
        assert st["shards"][1]["holder"] == "global_server:1"
        assert st["shards"][1]["term"] == 0
        # wait for the standby to hold shard 1's state, then kill
        sb1 = sim.standby_globals[1]
        k1 = encode_tensor(1, 16, 2)[0].ps_key
        assert _wait_for(lambda: k1 in sb1.store), "replication stalled"
        sim.kill_global_server(1)
        # the surviving shard keeps completing rounds while shard 1 is
        # dark; pump + tick until the health engine calls the stall
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            _run_rounds(sim, 1, tids=(0,), n=16)
            sim.pump_metrics()
            sim.health.tick()
            if sim.health.active_alerts():
                break
            time.sleep(0.05)
        active = [(a["rule"], a["subject"])
                  for a in sim.health.active_alerts()]
        assert ("round_stall", "shard:1") in active, active
        # promotion lands; the console shows it within one collection
        # interval of the next sweep
        assert _wait_for(lambda: not sb1.is_standby), "promotion stalled"
        st = sim.cluster_state()
        assert st["shards"][1]["holder"] == "standby_global:1"
        assert st["shards"][1]["term"] == 1
        assert st["shards"][1]["promoted"] is True
        assert st["shards"][0]["holder"] == "global_server:0"
        assert st["shards"][0]["term"] == 0
        # shard 1's stalled round replays at the standby; progress =
        # recovery record
        _run_rounds(sim, 1, tids=(1,), n=16)
        sim.pump_metrics()
        sim.health.tick()
        stall = [r for r in sim.health.alerts
                 if r["rule"] == "round_stall"
                 and r["subject"] == "shard:1"]
        assert [r["state"] for r in stall] == ["firing", "recovered"], \
            stall
        # exactly one alert for that shard; the surviving shard never
        # alerted
        assert not [r for r in sim.health.alerts
                    if r["rule"] == "round_stall"
                    and r["subject"] == "shard:0"]
        # alerts also landed in the registry
        snap = system_snapshot("global_scheduler:0.")
        assert snap["global_scheduler:0.health_alerts"] == 1
        assert snap["global_scheduler:0.health_recoveries"] == 1
        assert snap["global_scheduler:0.health_round_stall_alerts"] == 1
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# disabled-path guard
# ---------------------------------------------------------------------------

def test_disabled_obs_no_frames_no_threads():
    """Default config (GEOMX_OBS off): no collector, no pump, no
    telemetry threads, and a full training round puts zero
    METRICS_REPORT frames on the wire — the PR 3 trace-guard style
    'behavior unchanged' check."""
    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=1)))
    try:
        assert sim.metrics_collector is None
        assert sim.health is None
        assert not sim.metrics_pumps
        names = {t.name for t in threading.enumerate()}
        assert not any(n.startswith(("metrics-pump", "health-engine"))
                       for n in names), names
        seen = []
        orig = sim.fabric.deliver
        sim.fabric.deliver = lambda m: (seen.append(m), orig(m))[1]
        w = sim.worker(0, 0)
        w.init(0, np.zeros(32, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        w.push(0, np.ones(32, np.float32))
        w.pull_sync(0)
        w.wait_all()
        assert seen, "tap saw no traffic"
        assert not [m for m in seen
                    if m.cmd == int(Ctrl.METRICS_REPORT)]
        # the console itself stays available (costs nothing until
        # queried) but reports no telemetry
        st = sim.cluster_state()
        assert st["telemetry"] is None and st["health"] is None
    finally:
        sim.shutdown()


def test_obs_interval_runs_pump_and_health_threads():
    """obs_interval_s > 0: samples accumulate without manual pumping
    (the operator path the launcher uses)."""
    sim = Simulation(_obs_cfg(parties=1, obs_interval_s=0.05))
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 1, n=8)
        mc = sim.metrics_collector
        assert _wait_for(lambda: mc.reports_received >= 8, timeout=10)
        assert _wait_for(
            lambda: mc.value("global_server:0", "key_rounds") == 1,
            timeout=10)
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# restart discrimination (QUERY_STATS uptime/boot satellite)
# ---------------------------------------------------------------------------

def test_query_stats_uptime_and_boot_both_tiers():
    """QUERY_STATS now answers uptime_s/boot on both tiers, and a
    warm-booted replacement's counter reset is fenced by the collector
    (node_restarts bumps, no negative rates) instead of reading as a
    rate collapse."""
    cfg = _obs_cfg(parties=1, heartbeat_interval_s=0.05,
                   heartbeat_timeout_s=0.4, request_retry_s=0.4)
    sim = Simulation(cfg)
    try:
        kv = sim.worker(0, 0)
        kv.init(0, np.zeros(8, np.float32))
        kv.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 2, n=8)
        ls_stats = kv.worker.send_cmd(sim.topology.server(0),
                                      Ctrl.QUERY_STATS,
                                      domain=Domain.LOCAL)
        gs_stats = kv.worker.send_cmd(sim.topology.global_servers()[0],
                                      Ctrl.QUERY_STATS,
                                      domain=Domain.GLOBAL)
        for st in (ls_stats, gs_stats):
            assert st["uptime_s"] >= 0.0
            assert st["boot"] > 0
        old_boot = ls_stats["boot"]
        sim.pump_metrics()
        sim.pump_metrics()
        mc = sim.metrics_collector
        # replace the local server (same identity, new boot)
        sim.kill_local_server(0)
        sim.restart_local_server(0)
        assert _wait_for(
            lambda: (sim.local_servers[0].po.van.boot != old_boot))
        sim.pump_metrics()
        assert mc.node_restarts.get("server:0@p0") == 1
        # the fenced ring restarts: rates need two fresh samples and
        # can never span the reset
        sim.pump_metrics()
        r = mc.rate("server:0@p0", "wan_send_bytes")
        assert r is None or r >= 0.0
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# health rules over synthetic series
# ---------------------------------------------------------------------------

def _synthetic_engine(**cfg_kw):
    """A live 1-party sim whose collector we feed synthetic foreign
    samples — rule units run against controlled series."""
    cfg_kw.setdefault("obs_window", 8)
    sim = Simulation(_obs_cfg(parties=1, **cfg_kw))
    return sim, sim.metrics_collector, sim.health


def test_health_rule_replication_lag_and_rtt():
    sim, mc, eng = _synthetic_engine()
    try:
        mc.ingest({"node": "global_server:9", "boot": 7, "t_mono": 1.0,
                   "metrics": {"global_server:9.replication_lag_s": 120.0,
                               "global_server:9.heartbeat_rtt_s": 2.5},
                   "stats": {}})
        recs = eng.tick(now=10.0)
        got = {(r["rule"], r["subject"], r["state"]) for r in recs}
        assert ("replication_lag", "global_server:9",
                "firing") in got, recs
        assert ("rtt_outlier", "global_server:9", "firing") in got
        # second tick: still firing -> NO duplicate records
        assert not eng.tick(now=11.0)
        mc.ingest({"node": "global_server:9", "boot": 7, "t_mono": 2.0,
                   "metrics": {"global_server:9.replication_lag_s": 0.5,
                               "global_server:9.heartbeat_rtt_s": 0.01},
                   "stats": {}})
        recs = eng.tick(now=12.0)
        got = {(r["rule"], r["subject"], r["state"]) for r in recs}
        assert ("replication_lag", "global_server:9",
                "recovered") in got
        assert ("rtt_outlier", "global_server:9", "recovered") in got
        assert not eng.active_alerts()
    finally:
        sim.shutdown()


def test_health_rule_goodput_collapse_and_fence_spike():
    sim, mc, eng = _synthetic_engine(obs_goodput_frac=0.1,
                                     obs_fence_spike=8)
    try:
        node = "server:0@p9"
        # healthy phase: 10 MB/s, rounds progressing
        for i in range(4):
            mc.ingest({"node": node, "boot": 3, "t_mono": float(i),
                       "metrics": {},
                       "stats": {"wan_send_bytes": i * 10_000_000,
                                 "wan_push_rounds": i,
                                 "eviction_fenced_pushes": 0}})
        assert not [r for r in eng.tick(now=4.0)
                    if r["subject"] == node]
        # collapse phase: bytes crawl while rounds still tick over, and
        # the fence counter spikes
        for i in range(4, 8):
            mc.ingest({"node": node, "boot": 3,
                       "t_mono": float(i * 10),
                       "metrics": {},
                       "stats": {"wan_send_bytes":
                                 40_000_000 + i * 1_000,
                                 "wan_push_rounds": i,
                                 "eviction_fenced_pushes": (i - 3) * 5}})
        recs = eng.tick(now=80.0)
        got = {(r["rule"], r["state"]) for r in recs
               if r["subject"] == node}
        assert ("goodput_collapse", "firing") in got, recs
        assert ("fence_spike", "firing") in got, recs
        # recovery: the ring refills with healthy samples
        for i in range(8, 16):
            mc.ingest({"node": node, "boot": 3,
                       "t_mono": 80.0 + (i - 8),
                       "metrics": {},
                       "stats": {"wan_send_bytes":
                                 50_000_000 + (i - 8) * 10_000_000,
                                 "wan_push_rounds": i,
                                 "eviction_fenced_pushes": 25}})
        recs = eng.tick(now=90.0)
        got = {(r["rule"], r["state"]) for r in recs
               if r["subject"] == node}
        assert ("goodput_collapse", "recovered") in got, recs
        assert ("fence_spike", "recovered") in got, recs
    finally:
        sim.shutdown()


def test_health_alert_log_jsonl(tmp_path):
    """Alert records are appended to the configured JSONL log, each
    line parseable (the NaN fence applies here too)."""
    log = tmp_path / "alerts.jsonl"
    sim, mc, eng = _synthetic_engine(obs_alert_log=str(log))
    try:
        mc.ingest({"node": "global_server:9", "boot": 1, "t_mono": 1.0,
                   "metrics": {"global_server:9.replication_lag_s":
                               float(10 ** 3)},
                   "stats": {}})
        eng.tick(now=5.0)
        mc.ingest({"node": "global_server:9", "boot": 1, "t_mono": 2.0,
                   "metrics": {"global_server:9.replication_lag_s": 0.1},
                   "stats": {}})
        eng.tick(now=6.0)
        lines = [json.loads(ln) for ln in
                 log.read_text().strip().splitlines()]
        assert [ln["state"] for ln in lines] == ["firing", "recovered"]
        assert lines[0]["rule"] == "replication_lag"
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# controller reads collected series
# ---------------------------------------------------------------------------

def test_adaptive_controller_reads_collected_series():
    """With the telemetry plane on, the adaptive-WAN controller serves
    its sweeps from the collector's rings instead of issuing its own
    QUERY_STATS round trips."""
    cfg = _obs_cfg(parties=1, adaptive_wan=True, adapt_interval_s=0.0)
    sim = Simulation(cfg)
    try:
        w = sim.all_workers()[0]
        w.init(0, np.zeros(8, np.float32))
        w.set_optimizer({"type": "sgd", "lr": 0.1})
        _run_rounds(sim, 2, n=8)
        sim.pump_metrics()
        before = sim.wan_controller.metrics_samples
        sim.wan_controller.tick()
        assert sim.wan_controller.metrics_samples == before + 1
        # the sampled stats carried the real round counter
        sig = sim.wan_controller.signals
        assert sig._rounds["server:0@p0"]._q[-1][1] == 2.0
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# registry reset satellite
# ---------------------------------------------------------------------------

def test_reset_system_metrics_isolation():
    """reset_system_metrics wipes the registry; stale handles keep
    working without resurrecting their names — the autouse conftest
    fixture gives every test a clean slate."""
    c = system_counter("test_reset.counter")
    c.inc(5)
    system_gauge("test_reset.gauge").set(2.0)
    assert system_snapshot("test_reset.") == {
        "test_reset.counter": 5, "test_reset.gauge": 2.0}
    reset_system_metrics()
    assert system_snapshot("test_reset.") == {}
    c.inc()  # the orphan handle must not reappear in the registry
    assert system_snapshot("test_reset.") == {}
    # a re-registration starts from zero (no bleed from the orphan)
    assert system_counter("test_reset.counter").value == 0


def test_registry_clean_slate_between_simulations():
    """Regression for the cross-Simulation bleed: two sequential sims
    under resets see absolute counter values, not accumulations."""
    for _ in range(2):
        sim = Simulation(_obs_cfg(parties=1))
        try:
            w = sim.all_workers()[0]
            w.init(0, np.zeros(8, np.float32))
            w.set_optimizer({"type": "sgd", "lr": 0.1})
            _run_rounds(sim, 1, n=8)
            sim.pump_metrics()
            assert system_snapshot(
                "global_scheduler:0.")["global_scheduler:0.obs_reports"] \
                == sim.metrics_collector.reports_received
        finally:
            sim.shutdown()
        reset_system_metrics()
        assert system_snapshot("global_scheduler:0.") == {}
