#!/usr/bin/env python
"""Reference example-file parity: cnn_hfa.py == cnn.py --hfa
(ref: examples/cnn_hfa.py in the reference)."""
import sys
sys.argv[1:1] = "--hfa".split()
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parent))
from cnn import main

if __name__ == "__main__":
    sys.exit(main())
