"""Wire codecs: FP16, 2-bit quantization, Bi-Sparse top-k, MPQ.

Reimplements the reference GradientCompression family
(ref: src/kvstore/gradient_compression.{h,cc,-inl.h}) as stateful
host-side codecs applied at the WAN edge (local server ↔ global server):

- **FP16** — plain half-precision transmission, 2× reduction
  (ref: README.md:22; fp16 push paths kvstore_dist_server.h:760-820).
- **2-bit** — elementwise {−t, 0, +t} quantization with residual
  feedback, 4 values per byte = 16× vs float32
  (ref: gradient_compression-inl.h:40-139 — 16:1 packing, residual kept
  client-side and folded into the next round).
- **BSC (Bi-Sparse)** — DGC-style top-k sparsification with momentum
  correction and sampled-threshold estimation
  (ref: gradient_compression.cc:191-269 BSCompress — momentum m=0.9,
  accumulated velocity, 0.5% random sample to pick the threshold, emit
  [values ‖ indices]).  The pull direction re-sparsifies what flows back
  down (ref: BSCPullCompress :271-308) — implemented here as
  ``BroadcastCompressor``: per-(key, subscriber) top-k weight *deltas*
  with residual carry, so every byte down the WAN is also sparse.
- **MPQ** — mixed precision by size: tensors under ``size_bound`` go FP16,
  big ones BSC (ref: kvstore_dist_server.h:183, examples/cnn_mpq.py).

Wire format: a payload numpy array per key (dtype carries the encoding) +
the message-level ``compr`` tag.  Sparse payloads pack
``[float32 values ‖ int32 indices bit-cast to float32]`` like the
reference's [values ‖ indices] layout; the receiver recovers indices by
re-viewing the bits, so no precision is lost.

These run on the server hosts (numpy).  The worker-side/TPU variants of
the same math (for on-device compression before the host handoff) live in
geomx_tpu/ops as jax/pallas kernels.
"""

from __future__ import annotations

import weakref
from typing import Dict, Optional, Tuple

import numpy as np


def _native():
    """The C++ hot-loop library (geomx_tpu/native), or None — numpy
    remains the fallback and the semantic reference."""
    try:
        from geomx_tpu.native import bindings

        return bindings.lib()
    except Exception:  # pragma: no cover - missing toolchain
        return None


class CodecError(ValueError):
    """A payload failed structural validation at decode time.

    Every decode entry point raises THIS (never a bare IndexError /
    ValueError / reshape error, and never a silent wrong-shaped tensor)
    when a payload is truncated, mis-sized, or carries out-of-range
    indices — so receivers can fence the one bad push instead of letting
    a corrupt buffer take down the merge thread or, worse, scatter into
    the wrong coordinates.  Subclasses ValueError so pre-existing
    catch-sites keep working."""

    def __init__(self, what: str, *, tag: str = "", key: int = -1):
        self.what = what
        self.tag = tag
        self.key = int(key)
        detail = f" (tag '{tag}'" + (f", key {key})" if key >= 0 else ")") \
            if tag else (f" (key {key})" if key >= 0 else "")
        super().__init__(f"corrupt codec payload: {what}{detail}")


def _check_f32_vector(payload: np.ndarray, tag: str, key: int) -> np.ndarray:
    """Common structural gate for the bit-cast sparse formats: the
    [values ‖ indices] layouts re-view raw bits as int32, which is only
    meaningful on a contiguous 1-D 4-byte-item array."""
    arr = np.asarray(payload)
    if arr.ndim != 1:
        raise CodecError(f"expected 1-D payload, got ndim={arr.ndim}",
                         tag=tag, key=key)
    if arr.dtype.itemsize != 4:
        raise CodecError(
            f"expected 4-byte items for index bit-cast, got {arr.dtype}",
            tag=tag, key=key)
    # bit-cast (never a value conversion): the indices half only decodes
    # correctly if the raw 4-byte patterns are preserved
    return np.ascontiguousarray(arr).view(np.float32)


class Codec:
    name = "none"

    def compress(self, key: int, arr: np.ndarray) -> np.ndarray:
        return arr

    def decompress(self, key: int, payload: np.ndarray, orig_len: int) -> np.ndarray:
        return payload

    @property
    def dense_delta(self) -> bool:
        """True if decompressed output is a delta to ADD (sparse codecs)
        rather than a full replacement value."""
        return False


class Fp16Codec(Codec):
    name = "fp16"

    def compress(self, key, arr):
        return arr.astype(np.float16)

    def decompress(self, key, payload, orig_len):
        if len(payload) != orig_len:
            raise CodecError(
                f"fp16 payload carries {len(payload)} values for a "
                f"{orig_len}-element tensor", tag="fp16", key=key)
        return payload.astype(np.float32)


class TwoBitCodec(Codec):
    """{−t, 0, +t} with residual feedback; 4 values/byte.

    ref: gradient_compression-inl.h:40-139 (quantize_2bit: residual +=
    grad; emit ±threshold where |residual| > threshold; subtract emitted).
    """

    name = "2bit"

    def __init__(self, threshold: float = 0.5):
        self.threshold = float(threshold)
        self._residual: Dict[int, np.ndarray] = {}

    def compress(self, key, arr):
        n = len(arr)
        r = self._residual.get(key)
        if r is None or len(r) != n:
            r = np.zeros(n, dtype=np.float32)
        nlib = _native()
        if nlib is not None:
            g = np.ascontiguousarray(arr, dtype=np.float32)
            r = np.ascontiguousarray(r)
            out = np.zeros((n + 3) // 4, dtype=np.uint8)
            nlib.geo_pack2bit(g, r, out, n, self.threshold)
            self._residual[key] = r  # updated in place
            return out
        r = r + arr.astype(np.float32)
        q = np.zeros(n, dtype=np.uint8)  # 0 = zero, 1 = +t, 2 = −t
        q[r > self.threshold] = 1
        q[r < -self.threshold] = 2
        # in-place float32 updates (a `(q==1)*threshold` expression would
        # silently promote the stored residual to float64)
        r[q == 1] -= np.float32(self.threshold)
        r[q == 2] += np.float32(self.threshold)
        self._residual[key] = r
        # pack 4 two-bit codes per byte
        pad = (-len(q)) % 4
        qp = np.pad(q, (0, pad)).reshape(-1, 4)
        packed = (qp[:, 0] | (qp[:, 1] << 2) | (qp[:, 2] << 4) | (qp[:, 3] << 6))
        return packed.astype(np.uint8)

    def decompress(self, key, payload, orig_len):
        b = np.ascontiguousarray(payload, dtype=np.uint8)
        if len(b) < (orig_len + 3) // 4:
            # length gate BEFORE either decoder touches the buffer: the
            # native geo_unpack2bit reads orig_len/4 bytes unchecked (a
            # truncated payload would read out of bounds), and the numpy
            # path would return a silently short boolean mask
            raise CodecError(
                f"2bit payload holds {len(b) * 4} codes for a "
                f"{orig_len}-element tensor", tag="2bit", key=key)
        nlib = _native()
        if nlib is not None:
            out = np.empty(orig_len, dtype=np.float32)
            nlib.geo_unpack2bit(b, out, orig_len, self.threshold)
            return out
        q = np.empty((len(b), 4), dtype=np.uint8)
        q[:, 0] = b & 3
        q[:, 1] = (b >> 2) & 3
        q[:, 2] = (b >> 4) & 3
        q[:, 3] = (b >> 6) & 3
        q = q.reshape(-1)[:orig_len]
        out = np.zeros(orig_len, dtype=np.float32)
        out[q == 1] = self.threshold
        out[q == 2] = -self.threshold
        return out


def pack_sparse(values: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """[float32 values ‖ int32 indices bit-cast to float32]
    (ref wire layout: gradient_compression.cc:219-269 emits values then
    indices in one buffer)."""
    return np.concatenate([
        values.astype(np.float32),
        indices.astype(np.int32).view(np.float32),
    ])


def unpack_sparse(payload: np.ndarray, *, tag: str = "bsc",
                  key: int = -1) -> Tuple[np.ndarray, np.ndarray]:
    payload = _check_f32_vector(payload, tag, key)
    if len(payload) % 2 != 0:
        raise CodecError(
            f"sparse payload must be [values ‖ indices] (even length, "
            f"got {len(payload)})", tag=tag, key=key)
    k = len(payload) // 2
    values = payload[:k].astype(np.float32)
    indices = payload[k:].view(np.int32).astype(np.int64)
    return values, indices


def pack_rows(row_ids: np.ndarray, rows: np.ndarray) -> np.ndarray:
    """Row-sparse wire format: [rows.ravel() ‖ int32 row_ids bit-cast]
    (one definition for the four client/server codec sites)."""
    return np.concatenate([
        np.asarray(rows, np.float32).ravel(),
        np.asarray(row_ids, np.int64).astype(np.int32).view(np.float32),
    ])


def unpack_rows(payload: np.ndarray, cols: int):
    """Inverse of pack_rows → (row_ids int64 [k], rows float32 [k, cols])."""
    if cols < 1:
        raise CodecError(f"row-sparse decode needs cols >= 1, got {cols}",
                         tag="rows")
    payload = _check_f32_vector(payload, "rows", -1)
    if len(payload) % (cols + 1) != 0:
        raise CodecError(
            f"row-sparse payload of {len(payload)} values does not "
            f"split into (row ‖ id) groups of {cols + 1}", tag="rows")
    k = len(payload) // (cols + 1)
    rows = payload[:k * cols].reshape(k, cols).astype(np.float32)
    row_ids = payload[k * cols:].view(np.int32).astype(np.int64)
    return row_ids, rows


def _check_index_bounds(idx: np.ndarray, orig_len: int, tag: str,
                        key: int) -> None:
    """Reject out-of-range scatter indices BEFORE any write: a negative
    int32 from a flipped bit would silently wrap through numpy fancy
    indexing into the wrong coordinate, and the native geo_sparse_add
    would write out of bounds."""
    if len(idx) and (int(idx.min()) < 0 or int(idx.max()) >= orig_len):
        raise CodecError(
            f"scatter index out of range [0, {orig_len}) "
            f"(min {int(idx.min())}, max {int(idx.max())})",
            tag=tag, key=key)


def scatter_sparse(payload: np.ndarray, orig_len: int, *,
                   key: int = -1) -> np.ndarray:
    """Densify a [values ‖ indices] payload (shared by all bsc decoders)."""
    vals, idx = unpack_sparse(payload, key=key)
    _check_index_bounds(idx, orig_len, "bsc", key)
    out = np.zeros(orig_len, dtype=np.float32)
    out[idx] = vals
    return out


class BscCodec(Codec):
    """Bi-Sparse push-direction compressor (DGC-style).

    velocity = m·velocity + grad;  accum += velocity;  threshold from a
    random sample of |accum|;  emit top entries;  zero velocity+accum at
    emitted coordinates (ref: gradient_compression.cc:191-269).
    """

    name = "bsc"

    def __init__(self, ratio: float = 0.01, momentum: float = 0.9,
                 sample_rate: float = 0.005, seed: int = 0):
        import threading

        self.ratio = float(ratio)
        self.momentum = float(momentum)
        self.sample_rate = float(sample_rate)
        self._velocity: Dict[int, np.ndarray] = {}
        self._accum: Dict[int, np.ndarray] = {}
        self._rng = np.random.default_rng(seed)
        # np.random.Generator is not thread-safe; the parallel WAN
        # encode pool compresses different KEYS concurrently (per-key
        # velocity/accum never collide) but they share this sampler
        self._rng_mu = threading.Lock()

    def _threshold(self, arr: np.ndarray) -> float:
        """Sampled |.|-quantile threshold.  Takes the RAW array and
        abs-es only the sample — a full-array np.abs before sampling
        costs a 2x-tensor-size memory pass per push on the 50M hot
        path for values the sample never looks at."""
        n = len(arr)
        sample_n = max(int(n * self.sample_rate), min(n, 64))
        with self._rng_mu:
            idx = self._rng.integers(0, n, size=sample_n)
        sample = np.abs(arr[idx])
        # top `ratio` of the sample ⇒ quantile threshold
        return float(np.quantile(sample, max(0.0, 1.0 - self.ratio)))

    def compress(self, key, arr):
        g = np.ascontiguousarray(arr, dtype=np.float32)
        n = len(g)
        v = self._velocity.get(key)
        u = self._accum.get(key)
        if v is None or len(v) != n:
            v = np.zeros_like(g)
            u = np.zeros_like(g)
        cap = max(1, int(2 * self.ratio * n))
        nlib = _native()
        if nlib is not None:
            nlib.geo_dgc_update(v, u, g, n, self.momentum)  # in place
            thr = self._threshold(u)
            idx = np.empty(cap, dtype=np.int64)
            cnt = nlib.geo_select_threshold(u, n, thr, cap, idx)
            idx = idx[:cnt]
        else:
            v = self.momentum * v + g
            u = u + v
            mag = np.abs(u)
            thr = self._threshold(mag)
            mask = mag >= thr
            if not mask.any():
                mask[np.argmax(mag)] = True  # always send at least one entry
            idx = np.nonzero(mask)[0]
            # the sampled threshold is unstable on narrow magnitude
            # distributions (all-equal gradients would select 100%);
            # hard-cap the payload at 2x the target ratio via exact top-k
            if len(idx) > cap:
                top = np.argpartition(mag[idx], -cap)[-cap:]
                idx = idx[top]
        vals = u[idx]
        v[idx] = 0.0  # momentum factor masking (ref: DGC)
        u[idx] = 0.0
        self._velocity[key] = v
        self._accum[key] = u
        return pack_sparse(vals, idx)

    def decompress(self, key, payload, orig_len):
        return scatter_sparse(payload, orig_len, key=key)

    @property
    def dense_delta(self) -> bool:
        return True


class MpqSelector:
    """Mixed-precision: FP16 for small tensors, BSC for big ones
    (ref: kvstore_dist_server.h:183 MXNET_KVSTORE_SIZE_LOWER_BOUND)."""

    name = "mpq"

    def __init__(self, size_bound: int = 200_000, ratio: float = 0.01,
                 momentum: float = 0.9, sample_rate: float = 0.005):
        self.size_bound = int(size_bound)
        self.fp16 = Fp16Codec()
        self.bsc = BscCodec(ratio=ratio, momentum=momentum,
                            sample_rate=sample_rate)
        # split observability for acceptance runs / QUERY_STATS
        self.bsc_picks = 0
        self.fp16_picks = 0

    def select(self, size: int) -> Codec:
        if size >= self.size_bound:
            self.bsc_picks += 1
            return self.bsc
        self.fp16_picks += 1
        return self.fp16


def _sampled_topk_indices(delta: np.ndarray, ratio: float,
                          rng: np.random.Generator,
                          sample_rate: float = 0.005) -> np.ndarray:
    """Approximate top-|ratio| selection via a sampled quantile
    threshold + one capped scan — the reference's own BSC selection
    scheme (random-sample 0.5%, threshold from the sample, ref:
    gradient_compression.cc:191-269).  ~6x cheaper than the exact
    introselect at the 16.7M MultiGPS shard size (no full-array
    partition; the only full passes are sequential scans), at the cost
    of a payload that floats around the target ratio (hard-capped at
    2x, floor 1 entry)."""
    n = len(delta)
    sample_n = max(int(n * sample_rate), min(n, 64))
    sample = np.abs(delta[rng.integers(0, n, size=sample_n)])
    thr = float(np.quantile(sample, max(0.0, 1.0 - ratio)))
    cap = max(1, int(2 * ratio * n))
    nlib = _native()
    if nlib is not None:
        idx = np.empty(cap, dtype=np.int64)
        cnt = nlib.geo_select_threshold(delta, n, thr, cap, idx)
        if cnt == 0:
            # mirror the numpy fallback's argmax floor: a payload must
            # never be empty (an all-below-threshold scan — e.g. a NaN
            # quantile or float-compare edge — would otherwise ship 0
            # entries from native hosts while numpy hosts ship 1, and
            # the two builds' wire payloads must be identical)
            return np.array([int(np.argmax(np.abs(delta)))], dtype=np.int64)
        return idx[:cnt]
    mag = np.abs(delta)
    idx = np.flatnonzero(mag >= thr)
    if len(idx) == 0:
        return np.array([int(np.argmax(mag))], dtype=np.int64)
    if len(idx) > cap:
        top = np.argpartition(mag[idx], -cap)[-cap:]
        idx = idx[top]
    return idx


class BroadcastCompressor:
    """Pull-direction sparsifier (the second 'Bi' in Bi-Sparse).

    Per (subscriber, key): ship the top-k of (current weights − what the
    subscriber last received), accumulate the remainder as residual, and
    track the subscriber's view so it never desyncs
    (ref: BSCPullCompress kvstore_dist_server.h:1171-1211, :271-308 —
    the reference sparsifies the merged sum serving pulls; the delta+view
    formulation here is the TPU-build's numerically-safe equivalent).
    """

    def __init__(self, ratio: float = 0.01, trust_init: bool = True):
        self.ratio = float(ratio)
        # trust_init: the sparse-from-INIT fast path assumes every fresh
        # subscriber's replica equals the recorded INIT value.  True for
        # a compressor installed at SET_COMPRESSION / overwrite-INIT time
        # (the value was just propagated everywhere); MUST be False when
        # rebuilt from a checkpoint restore — subscribers still hold
        # whatever they last pulled, not the restored weights
        self.trust_init = bool(trust_init)
        self._view: Dict[Tuple[str, int], np.ndarray] = {}
        self._ver: Dict[Tuple[str, int], int] = {}
        self._init_values: Dict[int, np.ndarray] = {}
        # (subscriber, key) -> lineage token.  Two views share content
        # iff they share (lineage, ver): both start at "init" (the
        # propagated INIT value) and advance by the same cached deltas;
        # a dense RESYNC forks the subscriber onto a unique lineage —
        # its version numbers can collide with sparse-path peers'
        # (new_ver = max(echo, tracked)+1), so version alone must NEVER
        # authorize payload sharing (that applies a delta computed
        # against a different base: silent permanent replica corruption)
        self._lineage: Dict[Tuple[str, int], str] = {}
        # key -> (weakref(weights), lineage, ver, vals, idx): one top-k
        # per round serves every same-lineage-and-version subscriber.
        # weakref: a strong ref would pin the previous round's full
        # store array (~200 MB at the 50M hot path) until next compress
        self._payload_cache: Dict[int, tuple] = {}
        self._rng = np.random.default_rng(1234)  # sampled-threshold
        self.resyncs = 0  # forced dense resyncs (observability)

    def ensure_base(self, key: int, init_value: np.ndarray):
        self._init_values[key] = np.array(init_value, copy=True)

    def invalidate_key(self, key: int, new_init: np.ndarray):
        """Overwrite-INIT of ``key``: the new value was just propagated
        to every replica, so drop all subscribers' tracked views/versions
        for THIS key and re-seed its INIT base — echo-0 pulls re-enter
        the sparse-from-INIT path against the propagated value.  Other
        keys' handshake state stays untouched (a full rebuild would
        re-seed their INIT bases from trained weights that echo-0
        subscribers never held)."""
        self.ensure_base(key, new_init)
        self._payload_cache.pop(key, None)
        for pair in [p for p in self._view if p[1] == key]:
            del self._view[pair]
        for pair in [p for p in self._ver if p[1] == key]:
            del self._ver[pair]
        for pair in [p for p in self._lineage if p[1] == key]:
            # every subscriber re-enters sparse-from-INIT against the
            # NEW propagated value: back to the shared "init" lineage
            del self._lineage[pair]

    def drop_subscriber(self, subscriber: str) -> int:
        """Free every tracked view/version/lineage entry of one
        subscriber (a departed party server or an evicted serve
        replica).  Each view pins a full-model copy, so a server that
        never prunes leaks one model per subscriber that ever churned.
        Always SAFE to call on a live subscriber: a pruned pair's next
        pull takes the no-base branch of :meth:`compress` and resyncs
        dense — one extra dense response, never a wrong delta.  Returns
        the number of view arrays freed."""
        n = 0
        for pair in [p for p in self._view if p[0] == subscriber]:
            del self._view[pair]
            n += 1
        for pair in [p for p in self._ver if p[0] == subscriber]:
            del self._ver[pair]
        for pair in [p for p in self._lineage if p[0] == subscriber]:
            del self._lineage[pair]
        return n

    def subscribers(self) -> set:
        """Distinct subscriber ids with any tracked state
        (observability for the prune paths + their tests)."""
        return ({p[0] for p in self._view} | {p[0] for p in self._ver}
                | {p[0] for p in self._lineage})

    def compress(self, subscriber: str, key: int, weights: np.ndarray,
                 echo_ver: int = 0):
        """Encode one pull for ``subscriber``.

        ``echo_ver`` is the view version the subscriber last decoded
        (0 = fresh replica still at the INIT value).  Returns
        ``(payload, tag, new_ver)`` where tag is "bsc" (sparse delta) or
        "f32" (dense resync).  The version handshake is what makes the
        tracked view CRASH-SAFE: a restarted server has no view for the
        (subscriber, key) pair but the subscriber echoes ver>0 → the
        mismatch forces a dense resync instead of a delta against the
        wrong base, which silently corrupts a handful of top-k entries
        (observed: post-restart FSA desync in the 4x4 stress test).  A
        replaced subscriber echoes 0 against a tracked ver>0 — same
        resync.  Lost responses (replayed pulls) also mismatch and heal
        the same way."""
        tracked = self._ver.get((subscriber, key), 0)
        base = self._view.get((subscriber, key))
        if (base is None and tracked == 0 and echo_ver == 0
                and self.trust_init and (key in self._init_values)):
            # fresh pair on a server that has seen INIT: both sides hold
            # the INIT value (overwrite-INITs propagate to every replica
            # before pulls resume), so the first pull can already be
            # sparse.  No recorded INIT value (or a restore-rebuilt
            # compressor, trust_init=False) → dense resync below; a
            # guessed base here would corrupt the replica.
            base = self._init_values[key].copy()
        elif base is None or echo_ver != tracked:
            self.resyncs += 1
            new_ver = max(int(echo_ver), tracked) + 1
            w = np.ascontiguousarray(weights, dtype=np.float32)
            self._view[(subscriber, key)] = w.copy()
            self._ver[(subscriber, key)] = new_ver
            # fork onto a unique lineage: this subscriber's future
            # versions may numerically collide with sparse-path peers',
            # and the payload cache must never treat that as shared
            # content (confirmed corruption: one lost response -> peer's
            # delta applied to the resynced base, permanently wrong)
            self._lineage[(subscriber, key)] = f"resync{self.resyncs}"
            return w, "f32", new_ver
        # same-round payload reuse across subscribers (the 50M MultiGPS
        # hot path, VERDICT r4 item 4): subscribers on the SAME lineage
        # at the SAME version hold bit-identical views (both are INIT
        # plus the identical sequence of cached deltas), so the
        # (vals, idx) computed for the first subscriber of this
        # (weights, lineage, ver) triple serves the rest for the cost
        # of a scatter instead of a full selection scan.  Version alone
        # is NOT sufficient — a resynced subscriber's version collides
        # with sparse-path peers' (see _lineage).  Identity of the
        # weights ARRAY (via weakref, `is`, never id()) scopes the
        # cache to one optimizer round without pinning the old store.
        lineage = self._lineage.get((subscriber, key), "init")
        cached = self._payload_cache.get(key)
        if (cached is not None and cached[0]() is weights
                and cached[1] == lineage and cached[2] == tracked):
            vals, idx = cached[3], cached[4]
        else:
            # asarray, not astype: weights is the (frozen) f32 store
            # array in the hot path; astype would memcpy before the
            # subtract
            delta = np.ascontiguousarray(
                np.asarray(weights, np.float32) - base)
            idx = _sampled_topk_indices(delta, self.ratio, self._rng)
            vals = delta[idx]
            self._payload_cache[key] = (weakref.ref(weights), lineage,
                                        tracked, vals, idx)
        base[idx] += vals
        new_ver = tracked + 1
        self._view[(subscriber, key)] = base
        self._ver[(subscriber, key)] = new_ver
        return pack_sparse(vals, idx.astype(np.int64)), "bsc", new_ver

    @staticmethod
    def decompress_into(store_val: np.ndarray, payload: np.ndarray) -> np.ndarray:
        vals, idx = unpack_sparse(payload)
        _check_index_bounds(idx, len(store_val), "bsc", -1)
        out = np.ascontiguousarray(store_val, dtype=np.float32)
        if np.may_share_memory(out, store_val) or not out.flags.writeable:
            # ascontiguousarray of an already-contiguous same-dtype
            # input ALIASES it — copy only then (we mutate below and
            # must not write the caller's replica), or when the dtype
            # conversion produced a fresh-but-frozen array.  A
            # non-contiguous or non-f32 input already paid its one
            # conversion copy; the old unconditional .copy() stacked a
            # second full-model copy on every subscriber pull.
            out = out.copy()
        nlib = _native()
        if nlib is not None:
            nlib.geo_sparse_add(out, np.ascontiguousarray(vals),
                                np.ascontiguousarray(idx), len(idx))
        else:
            out[idx] += vals
        return out


def make_push_codec(config: dict):
    """Build the push-direction codec (or selector) from a SET_COMPRESSION
    body, e.g. {"type": "bsc", "ratio": 0.01}."""
    typ = config.get("type", "none")
    if typ == "none":
        return None
    if typ == "fp16":
        return Fp16Codec()
    if typ == "2bit":
        return TwoBitCodec(threshold=config.get("threshold", 0.5))
    if typ == "bsc":
        return BscCodec(ratio=config.get("ratio", 0.01),
                        momentum=config.get("momentum", 0.9),
                        sample_rate=config.get("sample_rate", 0.005))
    if typ == "mpq":
        return MpqSelector(size_bound=config.get("size_bound", 200_000),
                           ratio=config.get("ratio", 0.01),
                           momentum=config.get("momentum", 0.9),
                           sample_rate=config.get("sample_rate", 0.005))
    raise ValueError(f"unknown compression type '{typ}'")


# Wire tags a gradient-push payload may legally carry ("" = vanilla
# uncompressed f32).  Receivers fence anything else at message-decode
# time instead of letting a bare ValueError poison the merge path.
KNOWN_PUSH_TAGS = frozenset(("", "fp16", "2bit", "bsc"))

# codecs whose payload semantics survive carrying WEIGHTS instead of
# gradients (HFA rounds exchange party-mean weights; residual-feedback /
# top-k-delta codecs assume a gradient stream and silently corrupt a
# weight exchange)
WEIGHT_SAFE_CODECS = frozenset(("none", "fp16"))


def compression_allowed(codec: str, *, inter_ts: bool = False,
                        hfa: bool = False) -> Tuple[bool, Optional[str]]:
    """THE compatibility matrix for WAN codecs vs. operating modes.

    One predicate shared by static config validation
    (``Config.__post_init__``), the runtime ``SET_COMPRESSION`` /
    ``SET_WAN_POLICY`` command gates, and the adaptive policy engine's
    ladder construction (``geomx_tpu/control/policy.py``) — so the
    rules can never drift.  Returns ``(ok, reason)``; ``reason`` is
    None when allowed.

    ``hfa=True`` is the RUNTIME-ACTUATION context (the adaptive policy
    ladder and SET_WAN_POLICY): under HFA only weight-safe codecs may
    be *switched to*, because the others either do nothing (the HFA K2
    push path bypasses the push codec with dense milestone deltas) or
    would corrupt a weight stream if they ever applied.  A STATIC
    config combining HFA with bsc/mpq stays legal — the HFA data path
    routes around gradient codecs with dense pushes and dense pulls
    (see test_hfa_with_bsc_pull_stays_dense_and_synced) — so config
    validation passes ``hfa=False``."""
    if codec not in ("none", "fp16", "2bit", "bsc", "mpq"):
        return False, f"unknown compression type '{codec}'"
    if inter_ts and codec in ("bsc", "mpq"):
        return False, (
            "enable_inter_ts cannot combine with bsc/mpq pull "
            "compression (per-subscriber sparsified deltas don't fit "
            "a shared relay payload); use fp16 or none")
    if hfa and codec not in WEIGHT_SAFE_CODECS:
        return False, (
            f"'{codec}' is not weight-safe: HFA rounds exchange party-"
            "mean weights, and residual/top-k gradient codecs corrupt a "
            "weight stream; use fp16 or none")
    return True, None


class DecoderBank:
    """Per-endpoint stateful-decoder cache (bounded, LRU).

    Replaces the old module-level ``_TWOBIT_DECODERS`` dict, which was
    shared across every Simulation in one process and unbounded across
    thresholds: two concurrent deployments decoding 2-bit payloads with
    different thresholds hit the same instances, and any future decoder
    that keeps per-key state (residuals, bases) would silently leak one
    run's state into another.  Each receiving server owns one bank."""

    def __init__(self, cap: int = 32):
        import collections
        import threading

        self._cap = int(cap)
        self._decoders: "collections.OrderedDict" = collections.OrderedDict()
        # the parallel decode pool hits one endpoint's bank from
        # several threads; the LRU reorder needs real mutual exclusion
        self._mu = threading.Lock()

    def twobit(self, threshold: float) -> TwoBitCodec:
        key = ("2bit", float(threshold))
        with self._mu:
            dec = self._decoders.get(key)
            if dec is None:
                dec = self._decoders[key] = TwoBitCodec(threshold)
            self._decoders.move_to_end(key)
            while len(self._decoders) > self._cap:
                self._decoders.popitem(last=False)
        return dec

    def clear(self) -> None:
        """Drop all decoder state (a policy-epoch switch installs fresh
        codec parameters; stale residual-bearing decoders must not
        outlive the epoch that created them)."""
        with self._mu:
            self._decoders.clear()


def decompress_payload(compr: str, key: int, payload: np.ndarray,
                       orig_len: int, threshold: float = 0.5,
                       bank: Optional[DecoderBank] = None) -> np.ndarray:
    """Decode by tag (receiver side).  ``bank`` scopes stateful decoders
    to the calling endpoint; without one a fresh (stateless-for-decode)
    codec is used."""
    if compr == "fp16":
        if len(payload) != orig_len:
            raise CodecError(
                f"fp16 payload carries {len(payload)} values for a "
                f"{orig_len}-element tensor", tag="fp16", key=key)
        return payload.astype(np.float32)
    if compr == "bsc":
        return scatter_sparse(payload, orig_len, key=key)
    if compr == "2bit":
        dec = bank.twobit(threshold) if bank is not None \
            else TwoBitCodec(threshold)
        return dec.decompress(key, payload, orig_len)
    raise CodecError(f"unknown compr tag '{compr}'", tag=compr, key=key)
