// Native hot loops for the wire codecs.
//
// The reference implements its codec kernels natively too
// (ref: src/kvstore/gradient_compression.{cc,-inl.h} — C++/CUDA 2-bit
// pack/unpack with residual feedback, BSC top-k scan).  These are the
// host-side equivalents for the TPU build's server processes: the slab
// math that runs per push/pull on local/global servers.  Exposed C ABI,
// bound from Python via ctypes (geomx_tpu/native/bindings.py); the numpy
// implementations remain as the fallback and as the reference semantics
// for the equivalence tests.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// 2-bit quantization with residual feedback
// (ref: gradient_compression-inl.h:40-139).
// residual += grad; codes: 0 = zero, 1 = +t, 2 = -t; residual -= emitted.
// out must hold (n + 3) / 4 bytes.
void geo_pack2bit(const float* grad, float* residual, uint8_t* out,
                  int64_t n, float threshold) {
  const int64_t nbytes = (n + 3) / 4;
  std::memset(out, 0, nbytes);
  for (int64_t i = 0; i < n; ++i) {
    float r = residual[i] + grad[i];
    uint8_t q = 0;
    if (r > threshold) {
      q = 1;
      r -= threshold;
    } else if (r < -threshold) {
      q = 2;
      r += threshold;
    }
    residual[i] = r;
    out[i >> 2] |= static_cast<uint8_t>(q << ((i & 3) << 1));
  }
}

void geo_unpack2bit(const uint8_t* in, float* out, int64_t n,
                    float threshold) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t q = (in[i >> 2] >> ((i & 3) << 1)) & 3;
    out[i] = q == 1 ? threshold : (q == 2 ? -threshold : 0.0f);
  }
}

// DGC-style momentum-correction update for BSC
// (ref: gradient_compression.cc:191-269):
//   v = m*v + g;  u += v
void geo_dgc_update(float* v, float* u, const float* g, int64_t n, float m) {
  for (int64_t i = 0; i < n; ++i) {
    v[i] = m * v[i] + g[i];
    u[i] += v[i];
  }
}

// Exact top-k |u| selection (the cap path of BscCodec / the
// BroadcastCompressor pull sparsifier).  idx_out must hold k entries.
// Returns the number of selected indices (== k, clamped to n).
int64_t geo_topk_abs(const float* u, int64_t n, int64_t k, int64_t* idx_out) {
  if (k <= 0 || n <= 0) return 0;
  if (k > n) k = n;
  // select on a VALUE array, not an index array: nth_element with
  // indirect fabs(u[idx]) comparisons walks u at random (one cache
  // miss per compare) and measured ~2x slower than numpy's
  // argpartition at 16M elements; direct float compares on a
  // sequential copy are the fast path
  std::vector<float> mag(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) mag[i] = std::fabs(u[i]);
  std::nth_element(mag.begin(), mag.begin() + (k - 1), mag.end(),
                   std::greater<float>());
  const float thr = mag[k - 1];
  // two sequential passes: strictly-greater hits first (at most k-1 of
  // them), then ties at the threshold until k are collected
  int64_t cnt = 0;
  for (int64_t i = 0; i < n && cnt < k; ++i)
    if (std::fabs(u[i]) > thr) idx_out[cnt++] = i;
  for (int64_t i = 0; i < n && cnt < k; ++i)
    if (std::fabs(u[i]) == thr) idx_out[cnt++] = i;
  return cnt;
}

// Threshold selection with hard cap: gather indices with |u| >= thr; if
// more than cap, keep the cap largest.  Returns count.
int64_t geo_select_threshold(const float* u, int64_t n, float thr,
                             int64_t cap, int64_t* idx_out) {
  std::vector<int64_t> hits;
  hits.reserve(static_cast<size_t>(cap) * 2);
  for (int64_t i = 0; i < n; ++i) {
    if (std::fabs(u[i]) >= thr) hits.push_back(i);
  }
  if (hits.empty()) {
    int64_t best = 0;
    float bm = -1.0f;
    for (int64_t i = 0; i < n; ++i) {
      const float m = std::fabs(u[i]);
      if (m > bm) { bm = m; best = i; }
    }
    idx_out[0] = best;
    return 1;
  }
  if (static_cast<int64_t>(hits.size()) > cap) {
    std::nth_element(hits.begin(), hits.begin() + (cap - 1), hits.end(),
                     [&](int64_t a, int64_t b) {
                       return std::fabs(u[a]) > std::fabs(u[b]);
                     });
    hits.resize(cap);
  }
  std::sort(hits.begin(), hits.end());
  std::copy(hits.begin(), hits.end(), idx_out);
  return static_cast<int64_t>(hits.size());
}

// dense[idx[i]] += vals[i]  (sparse pull-delta application,
// ref: BSCDecompress :310-336)
void geo_sparse_add(float* dense, const float* vals, const int64_t* idx,
                    int64_t k) {
  for (int64_t i = 0; i < k; ++i) dense[idx[i]] += vals[i];
}

}  // extern "C"

#include <thread>

extern "C" {

// Threaded dense accumulate: acc += v, split across `threads` chunks
// (ref: the reference schedules server merges on the engine's worker
// pool, kvstore_dist_server.h:1277-1296 — here the parallelism lives
// INSIDE one merge so the Python per-key state machines stay
// single-writer).  threads <= 1 degenerates to a plain loop.
void geo_axpy_acc(float* acc, const float* v, int64_t n, int threads) {
  if (threads <= 1 || n < (1 << 20)) {
    for (int64_t i = 0; i < n; ++i) acc[i] += v[i];
    return;
  }
  std::vector<std::thread> pool;
  const int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([acc, v, lo, hi] {
      for (int64_t i = lo; i < hi; ++i) acc[i] += v[i];
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
