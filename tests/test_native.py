"""Native (C++) codec kernels: build, bind, and match the numpy reference."""

import numpy as np
import pytest

from geomx_tpu.native import bindings


def test_native_library_builds_and_loads():
    # the toolchain is part of this environment; the library must build
    assert bindings.available(), "libgeocodecs.so failed to build/load"


def test_pack2bit_matches_numpy_reference():
    nlib = bindings.lib()
    rng = np.random.default_rng(0)
    n = 1001  # non-multiple of 4 exercises the tail
    g = rng.standard_normal(n).astype(np.float32)
    thr = 0.5

    # native
    r_nat = np.zeros(n, np.float32)
    out_nat = np.zeros((n + 3) // 4, np.uint8)
    nlib.geo_pack2bit(g, r_nat, out_nat, n, thr)

    # numpy reference (the fallback path, inlined)
    r = g.copy()
    q = np.zeros(n, np.uint8)
    q[r > thr] = 1
    q[r < -thr] = 2
    r[q == 1] -= np.float32(thr)
    r[q == 2] += np.float32(thr)
    pad = (-n) % 4
    qp = np.pad(q, (0, pad)).reshape(-1, 4)
    out_ref = (qp[:, 0] | (qp[:, 1] << 2) | (qp[:, 2] << 4)
               | (qp[:, 3] << 6)).astype(np.uint8)

    np.testing.assert_array_equal(out_nat, out_ref)
    np.testing.assert_allclose(r_nat, r, rtol=1e-6)

    # round-trip through native unpack
    dec = np.empty(n, np.float32)
    nlib.geo_unpack2bit(out_nat, dec, n, thr)
    exp = np.zeros(n, np.float32)
    exp[q == 1] = thr
    exp[q == 2] = -thr
    np.testing.assert_array_equal(dec, exp)


def test_dgc_update_and_select():
    nlib = bindings.lib()
    n = 512
    rng = np.random.default_rng(1)
    g = rng.standard_normal(n).astype(np.float32)
    v = np.zeros(n, np.float32)
    u = np.zeros(n, np.float32)
    nlib.geo_dgc_update(v, u, g, n, 0.9)
    np.testing.assert_allclose(v, g, rtol=1e-6)
    np.testing.assert_allclose(u, g, rtol=1e-6)

    idx = np.empty(10, np.int64)
    cnt = nlib.geo_select_threshold(u, n, 1e9, 10, idx)
    assert cnt == 1  # nothing over threshold → the single argmax
    assert idx[0] == int(np.argmax(np.abs(u)))

    cnt = nlib.geo_select_threshold(u, n, 0.0, 10, idx)
    assert cnt == 10  # capped, keeps the 10 largest
    top10 = set(np.argsort(-np.abs(u))[:10].tolist())
    assert set(idx[:cnt].tolist()) == top10


def test_topk_and_sparse_add():
    nlib = bindings.lib()
    u = np.array([0.1, -5.0, 0.2, 3.0, -0.05], np.float32)
    idx = np.empty(2, np.int64)
    cnt = nlib.geo_topk_abs(u, 5, 2, idx)
    assert cnt == 2 and set(idx.tolist()) == {1, 3}

    dense = np.zeros(5, np.float32)
    nlib.geo_sparse_add(dense, np.array([1.5, -2.0], np.float32),
                        np.array([0, 4], np.int64), 2)
    np.testing.assert_allclose(dense, [1.5, 0, 0, 0, -2.0])

    # k=0 guard
    assert nlib.geo_topk_abs(u, 5, 0, idx) == 0


def test_codecs_use_native_and_stay_correct():
    """The TwoBit/Bsc codec classes, now on the native path, must still
    pass their semantic contracts (mass conservation, top-k)."""
    from geomx_tpu.compression import BscCodec, TwoBitCodec

    c = TwoBitCodec(threshold=0.5)
    g = np.full(256, 0.2, np.float32)
    total = np.zeros_like(g)
    for _ in range(50):
        total += c.decompress(0, c.compress(0, g), 256)
    assert 0.2 * 50 - 0.71 <= total.mean() <= 0.2 * 50 + 1e-5

    b = BscCodec(ratio=0.05, momentum=0.0, sample_rate=0.5, seed=0)
    x = np.zeros(1000, np.float32)
    x[::100] = np.arange(1, 11, dtype=np.float32)
    dense = b.decompress(0, b.compress(0, x), 1000)
    assert dense[900] == 10.0
    total = dense.copy()
    for _ in range(30):
        total += b.decompress(0, b.compress(0, np.zeros(1000, np.float32)), 1000)
    np.testing.assert_allclose(total, x, atol=1e-5)


def test_force_accum_override_and_eager_calibration(monkeypatch):
    """advisor r5: GEOMX_FORCE_ACCUM pins the accumulate backend
    outright, and the calibration runs via calibrate()/calibrate_async()
    at server startup — accumulate() itself must only consult the
    cached verdict (the merge path runs under the server lock)."""
    acc = np.arange(8, dtype=np.float32)
    v = np.ones(8, np.float32)

    monkeypatch.setenv("GEOMX_FORCE_ACCUM", "numpy")
    assert bindings.axpy_backend(4) == "numpy"
    bindings.accumulate(acc, v)
    np.testing.assert_allclose(acc, np.arange(8) + 1)

    if bindings.available() and hasattr(bindings.lib(), "geo_axpy_acc"):
        monkeypatch.setenv("GEOMX_FORCE_ACCUM", "native")
        assert bindings.axpy_backend(4) == "native"
        bindings.accumulate(acc, v, threads=2)
        np.testing.assert_allclose(acc, np.arange(8) + 2)

    monkeypatch.delenv("GEOMX_FORCE_ACCUM")
    # eager path: calibrate() returns a definite verdict and caches it,
    # so a subsequent locked-path accumulate never times anything
    backend = bindings.calibrate(2)
    assert backend in ("native", "numpy")
    if backend != "numpy":
        assert bindings._axpy_wins.get(2) is True
