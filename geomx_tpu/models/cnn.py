"""The reference demo CNN, TPU-native.

Architecture parity with the reference workload (ref: examples/cnn.py:32-45 —
Conv(32,3x3) → pool → Conv(64,3x3) → pool → Dense(128) → Dense(64) →
Dense(num_classes), ReLU activations, batch 32, Adam lr 0.01 on MNIST).
Implemented as a flax module compiled by XLA: convs/matmuls land on the
MXU; default compute dtype is bfloat16 with float32 params, the TPU-native
mixed-precision recipe.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class CNN(nn.Module):
    num_classes: int = 10
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # params stay float32; activations run in bf16 for the MXU
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Dense(64, dtype=self.compute_dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


def create_cnn_state(
    rng: jax.Array,
    input_shape: Tuple[int, ...] = (1, 28, 28, 1),
    num_classes: int = 10,
    compute_dtype: Any = jnp.bfloat16,
):
    """Init params + a jitted grad function.

    Returns (model, params, grad_fn) where
    ``grad_fn(params, x, y) -> (loss, acc, grads)`` is jit-compiled.
    """
    from geomx_tpu.models.common import make_grad_fn

    model = CNN(num_classes=num_classes, compute_dtype=compute_dtype)
    params = model.init(rng, jnp.zeros(input_shape, jnp.float32))
    return model, params, make_grad_fn(model)
