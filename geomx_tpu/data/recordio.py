"""Record-IO: length-prefixed binary record files with random access.

Mirrors the reference's dmlc record-IO storage layer (ref: src/io/ uses
3rdparty/dmlc-core/include/dmlc/recordio.h readers; python surface
python/mxnet/recordio.py — MXRecordIO/MXIndexedRecordIO + pack/unpack).
Wire format per record: ``[u32 magic | u32 lrec | payload | pad-to-4]``
with payload length in the low 29 bits of ``lrec``.  Indexing a file is
a single native scan (geomx_tpu/native/recordio.cc) with a pure-python
fallback.

On top of raw records, :func:`pack_array` / :func:`unpack_array` carry a
labelled ndarray (the reference's IRHeader + data payload,
ref: python/mxnet/recordio.py pack/unpack).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Tuple

import numpy as np

RECORD_MAGIC = 0xCED7230A
_LEN_MASK = (1 << 29) - 1

_ARRAY_MAGIC = 0x47584152  # "GXAR"
_DTYPES = {0: np.float32, 1: np.float16, 2: np.int32, 3: np.int64,
           4: np.uint8, 5: np.int8}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class RecordWriter:
    """Append-only record file writer (cold path — plain Python).
    Accepts a path or an open binary file object (not closed on exit —
    the atomic_write context manages it)."""

    def __init__(self, path_or_file):
        if hasattr(path_or_file, "write"):
            self._f = path_or_file
            self._owns = False
        else:
            self._f = open(path_or_file, "wb")
            self._owns = True

    def write(self, payload: bytes) -> None:
        if len(payload) > _LEN_MASK:
            raise ValueError(f"record too large: {len(payload)}")
        self._f.write(struct.pack("<II", RECORD_MAGIC, len(payload)))
        self._f.write(payload)
        pad = (-len(payload)) % 4
        if pad:
            self._f.write(b"\x00" * pad)

    def close(self) -> None:
        if self._owns:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _index_python(buf: bytes) -> List[Tuple[int, int]]:
    out = []
    pos, size = 0, len(buf)
    while pos + 8 <= size:
        magic, lrec = struct.unpack_from("<II", buf, pos)
        if magic != RECORD_MAGIC:
            raise IOError(f"corrupt record file at byte {pos}")
        n = lrec & _LEN_MASK
        if pos + 8 + n > size:
            raise IOError(f"truncated record at byte {pos}")
        out.append((pos + 8, n))
        pos += 8 + ((n + 3) & ~3)
    if pos != size:
        raise IOError(f"trailing garbage at byte {pos}")
    return out


def _index_native(buf) -> Optional[List[Tuple[int, int]]]:
    from geomx_tpu.native import bindings

    lib = bindings.lib()
    if lib is None or not hasattr(lib, "geo_recordio_index"):
        return None
    data = np.frombuffer(buf, dtype=np.uint8)
    cap = len(buf) // 8 + 1
    offsets = np.empty(cap, dtype=np.int64)
    lengths = np.empty(cap, dtype=np.int64)
    n = lib.geo_recordio_index(data, len(buf), cap, offsets, lengths)
    if n < 0:
        raise IOError(f"corrupt record file at byte {-n - 1}")
    return list(zip(offsets[:n].tolist(), lengths[:n].tolist()))


class RecordReader:
    """Random-access reader: whole file in memory + (offset, len) index.

    The reference splits sequential (MXRecordIO) and indexed
    (MXIndexedRecordIO w/ a .idx sidecar) readers; here the index is
    rebuilt by one native scan at open so no sidecar file is needed.
    The file is mmapped, so N readers of one file in a process (one per
    worker thread) share a single physical copy via the page cache."""

    def __init__(self, path: str):
        import mmap

        self._f = open(path, "rb")
        if os.path.getsize(path) == 0:
            self._buf: bytes = b""
        else:
            self._buf = mmap.mmap(self._f.fileno(), 0,
                                  access=mmap.ACCESS_READ)
        idx = _index_native(self._buf)
        self._index = idx if idx is not None else _index_python(self._buf)

    def __len__(self) -> int:
        return len(self._index)

    def read(self, i: int) -> bytes:
        off, n = self._index[i]
        return self._buf[off:off + n]

    def __iter__(self):
        for i in range(len(self)):
            yield self.read(i)


def pack_array(x: np.ndarray, label: float = 0.0) -> bytes:
    """Serialize one labelled ndarray into a record payload."""
    x = np.ascontiguousarray(x)
    code = _DTYPE_CODES.get(x.dtype)
    if code is None:
        raise TypeError(f"unsupported dtype {x.dtype}")
    hdr = struct.pack("<IBBHf", _ARRAY_MAGIC, code, x.ndim, 0, label)
    dims = struct.pack(f"<{x.ndim}q", *x.shape)
    return hdr + dims + x.tobytes()


def unpack_array(payload: bytes) -> Tuple[np.ndarray, float]:
    magic, code, ndim, _, label = struct.unpack_from("<IBBHf", payload, 0)
    if magic != _ARRAY_MAGIC:
        raise IOError("not an array record")
    dims = struct.unpack_from(f"<{ndim}q", payload, 12)
    data = np.frombuffer(payload, dtype=_DTYPES[code], offset=12 + 8 * ndim)
    return data.reshape(dims).copy(), label


def write_array_dataset(path: str, x: np.ndarray, y: np.ndarray) -> None:
    """Write a (features, labels) dataset as one record per example.

    The write is atomic (temp file + rename): an interrupted or
    concurrent writer can never leave a truncated file at ``path`` for
    later runs to trip over."""
    from geomx_tpu.utils.io import atomic_write

    with atomic_write(path) as f:
        w = RecordWriter(f)
        for xi, yi in zip(x, y):
            w.write(pack_array(xi, float(yi)))
