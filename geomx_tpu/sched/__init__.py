from geomx_tpu.sched.tsengine import TsScheduler, TsClient  # noqa: F401
