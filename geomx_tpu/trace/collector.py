"""Scheduler-side trace collector: merge + round critical-path report.

Runs on the global scheduler (the one node every party can reach over
the WAN domain).  Nodes batch-ship completed spans as
``Ctrl.TRACE_REPORT`` data-channel requests (fire-and-forget — no
response slot, so a dead collector never blocks training); the collector
owns the PS app id on the scheduler's postoffice, which otherwise serves
no data traffic.

Clock correction: each report carries the sender's heartbeat-RTT clock
offsets to its scheduler(s) (``Postoffice.clock_offsets``).  Offsets are
"scheduler clock minus my clock"; a worker only knows its party
scheduler, so its offset to the global clock is chained through its
party's local server, which heartbeats both tiers:

    off(worker -> global) = off(worker -> psched) + off(psched -> global)
    off(psched -> global) = off(server -> global) - off(server -> psched)

On one host all offsets are ~0; on real deployments this is the same
RTT/2 estimate NTP starts from — good to a few ms, enough to order
LAN-push vs WAN vs optimizer stages that differ by tens of ms.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

# span-name prefix -> critical-path stage (the push→merge→WAN→optimize→
# pull round trip of PAPER.md, plus the control stages)
_STAGES = (
    ("worker.push", "lan_push"),
    ("local.push", "local_merge"),
    ("local.init", "local_merge"),
    ("codec.", "codec"),
    ("wan.", "wan"),
    ("global.push", "global_merge"),
    ("global.opt", "global_merge"),
    ("global.init", "global_merge"),
    ("global.pull", "pull_fanout"),
    ("local.pull", "pull_fanout"),
    ("worker.pull", "pull_fanout"),
    ("barrier", "barrier"),
)


def _stage_of(name: str) -> Optional[str]:
    for prefix, stage in _STAGES:
        if name.startswith(prefix):
            return stage
    return None


def _party_of(node: str) -> str:
    return node.rsplit("@", 1)[1] if "@" in node else "central"


def _shard_of(node: str):
    """Global-tier shard rank of a node, or None.  The shard identity
    survives failover: ``standby_global:k`` serves exactly shard k's
    key range once promoted, so its spans bill to the same shard as the
    primary it replaced."""
    for role in ("global_server:", "standby_global:"):
        if node.startswith(role):
            try:
                return int(node[len(role):].split("@", 1)[0])
            except ValueError:
                return None
    return None


def resolve_clock_offsets(offs: Dict[str, Dict[str, float]],
                          gname: str) -> Dict[str, float]:
    """Per-node offset to the global scheduler's clock (seconds), from
    each node's heartbeat-echo offsets to its scheduler target(s) —
    the chaining documented in the module docstring.  Shared by the
    trace collector and the flight-recorder postmortem assembler
    (obs/postmortem.py), which rebases per-node dumps the same way."""
    out: Dict[str, float] = {gname: 0.0}
    # party-scheduler offsets chained through the party's server
    psched_to_g: Dict[str, float] = {}
    for n, o in offs.items():
        if gname in o:
            out[n] = o[gname]
            for sched, v in o.items():
                if sched != gname:
                    psched_to_g[sched] = o[gname] - v
                    out.setdefault(sched, o[gname] - v)
    for n, o in offs.items():
        if n in out:
            continue
        for sched, v in o.items():
            if sched in psched_to_g:
                out[n] = v + psched_to_g[sched]
                break
    return out


class TraceCollector:
    """One per deployment, on the global scheduler's postoffice."""

    def __init__(self, postoffice):
        from geomx_tpu.kvstore.common import Ctrl
        from geomx_tpu.obs.endpoint import get_endpoint

        self.po = postoffice
        self.node = str(postoffice.node)
        self._mu = threading.Lock()
        self._events: List[dict] = []
        self._offsets: Dict[str, Dict[str, float]] = {}
        self.reports_received = 0
        # sibling collectors (the metrics collector's perfetto counter
        # tracks) contribute events to the merged timeline through here
        self.extra_event_sources: List = []
        # the scheduler's PS app is shared with the other telemetry
        # collectors — one endpoint routes frames by Ctrl head
        self._endpoint = get_endpoint(postoffice).acquire()
        self._endpoint.route(Ctrl.TRACE_REPORT, self._on_msg)

    def _on_msg(self, msg):
        body = msg.body if isinstance(msg.body, dict) else {}
        self.ingest(body)

    def ingest(self, body: dict) -> None:
        node = str(body.get("node", "?"))
        spans = body.get("spans") or ()
        with self._mu:
            self._events.extend(spans)
            offs = body.get("offsets")
            if offs:
                self._offsets[node] = {str(k): float(v)
                                       for k, v in offs.items()}
            self.reports_received += 1

    # ---- clock-offset resolution -------------------------------------------
    def _resolve_offsets(self) -> Dict[str, float]:
        """Per-node offset to the global scheduler's clock (seconds)."""
        with self._mu:
            offs = {n: dict(o) for n, o in self._offsets.items()}
        out = resolve_clock_offsets(
            offs, str(self.po.topology.global_scheduler()))
        out.setdefault(self.node, 0.0)
        return out

    # ---- merge --------------------------------------------------------------
    def merged_events(self) -> List[dict]:
        """Every collected event, timestamps rebased onto the global
        scheduler's clock (``ts`` in µs from the earliest event)."""
        offsets = self._resolve_offsets()
        with self._mu:
            events = list(self._events)
        for src in list(self.extra_event_sources):
            try:
                events.extend(src())
            except Exception:  # a sibling mid-stop must not break dumps
                pass
        if not events:
            return []
        out = []
        for ev in events:
            node = ev.get("pid", "?")
            off_us = offsets.get(node, 0.0) * 1e6
            t = ev.get("args", {}).get("t_mono_us", ev.get("ts", 0.0))
            e = dict(ev)
            e["ts"] = t + off_us
            out.append(e)
        t_min = min(e["ts"] for e in out)
        for e in out:
            e["ts"] -= t_min
        out.sort(key=lambda e: e["ts"])
        return out

    def merged_trace(self) -> dict:
        """Chrome-trace/perfetto JSON of the whole deployment: one
        ``pid`` per node, spans linked by args.span/args.parent."""
        return {"traceEvents": self.merged_events(),
                "displayTimeUnit": "ms",
                "otherData": {"clock_offsets_s": self._resolve_offsets()}}

    def dump(self, path: str) -> dict:
        trace = self.merged_trace()
        with open(path, "w") as f:
            json.dump(trace, f)
        return trace

    # ---- critical path ------------------------------------------------------
    def critical_path(self) -> dict:
        """Per-round stage breakdown + straggler attribution.

        For each sampled round (one ``trace_id``): the wall window, the
        busy time per stage (WAN time is recovered from matched
        wan.send → wan.recv instants, everything else from span
        durations), the per-stage worst node (the straggler), and the
        ``dominant_stage`` — the stage with the largest busy share,
        i.e. the first place a perf PR should look.
        """
        events = self.merged_events()
        rounds: Dict[int, dict] = {}
        # wan.send spans by span-id, for pairing with their wan.recv
        sends: Dict[int, dict] = {}
        for ev in events:
            a = ev.get("args", {})
            if ev.get("name") == "wan.send" and a.get("span"):
                sends[a["span"]] = ev
        for ev in events:
            a = ev.get("args", {})
            tid = a.get("trace_id", 0)
            if not tid or tid < 0:
                continue
            r = rounds.setdefault(tid, {
                "trace_id": tid, "round": tid - 1, "t0": ev["ts"],
                "t1": ev["ts"], "num_spans": 0, "stages": {}, "events": [],
            })
            dur = float(ev.get("dur") or 0.0)
            r["t0"] = min(r["t0"], ev["ts"])
            r["t1"] = max(r["t1"], ev["ts"] + dur)
            r["num_spans"] += 1
            name = ev.get("name", "")
            stage = _stage_of(name)
            node = ev.get("pid", "?")
            if name == "wan.recv":
                send = sends.get(a.get("parent", -1))
                if send is not None:
                    dur = max(0.0, ev["ts"] - send["ts"])
                    node = send.get("pid", node)  # bill the sender's link
                else:
                    continue
            elif name == "wan.send" or dur <= 0.0:
                continue  # instants: wan time comes from the recv pair
            if stage is None:
                continue
            st = r["stages"].setdefault(stage, {
                "busy_us": 0.0, "worst_node": None, "worst_us": 0.0,
                "by_party": {}})
            st["busy_us"] += dur
            party = _party_of(node)
            st["by_party"][party] = st["by_party"].get(party, 0.0) + dur
            if dur > st["worst_us"]:
                st["worst_us"] = dur
                st["worst_node"] = node
            # sharded global tier: bill global-server work (and WAN
            # transit INTO a shard — the recv side of the matched pair)
            # to its shard, so the report names the slowest shard the
            # way it names the straggler party
            shard = _shard_of(str(ev.get("pid", node))
                              if name == "wan.recv" else node)
            if shard is not None:
                bs = r.setdefault("by_shard", {})
                bs[shard] = bs.get(shard, 0.0) + dur
        out = []
        for tid in sorted(rounds):
            r = rounds.pop(tid)
            r.pop("events", None)
            r["wall_us"] = r["t1"] - r["t0"]
            if r["stages"]:
                r["dominant_stage"] = max(
                    r["stages"], key=lambda s: r["stages"][s]["busy_us"])
                for st in r["stages"].values():
                    if st["by_party"]:
                        st["straggler_party"] = max(
                            st["by_party"], key=st["by_party"].get)
            else:
                r["dominant_stage"] = None
            if r.get("by_shard"):
                # the first place to look when shard-count scaling is
                # sublinear: which key range's server bounded the round
                r["slowest_shard"] = max(r["by_shard"],
                                         key=r["by_shard"].get)
            out.append(r)
        return {"rounds": out,
                "num_events": len(events),
                "clock_offsets_s": self._resolve_offsets()}

    def report_text(self) -> str:
        """Human-readable critical-path summary, one line per round."""
        cp = self.critical_path()
        lines = []
        for r in cp["rounds"]:
            stages = ", ".join(
                f"{s}={st['busy_us'] / 1e3:.1f}ms"
                + (f"(worst {st['worst_node']})" if st["worst_node"] else "")
                for s, st in sorted(r["stages"].items(),
                                    key=lambda kv: -kv[1]["busy_us"]))
            shard = (f" slowest_shard={r['slowest_shard']}"
                     if "slowest_shard" in r else "")
            lines.append(
                f"round {r['round']}: wall={r['wall_us'] / 1e3:.1f}ms "
                f"dominant={r['dominant_stage']}{shard} [{stages}]")
        return "\n".join(lines)

    def stop(self):
        self._endpoint.release()
