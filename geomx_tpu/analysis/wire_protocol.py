"""Checker: wire-protocol exhaustiveness for the four protocol enums
and the ``Message`` header field tables.

Rules:

``duplicate-enum-value``
    ``Control`` / ``Ctrl`` / ``Cmd`` / ``FlightEv`` values must be
    unique.  ``IntEnum`` silently aliases duplicate values — a
    copy-pasted value would make two protocol heads indistinguishable
    on the wire without any runtime error.

``undispatched-enum-member``
    Every member must be *referenced* outside its defining module, and
    (for the three command enums) referenced in at least one of the
    enum's receiver modules — adding a protocol head without a handler
    is dead wire surface at best and a silent drop at worst.  For
    ``FlightEv`` the requirement is a ``record(FlightEv.X`` call site
    anywhere (the postmortem renders codes generically by name, so the
    receiving role is the recorder itself).

``wire-field-table``
    The scalar fields packed by ``Message._pack_hdr`` define the wire
    header.  Every header field that is not per-chunk mechanics
    (seq/channel/offset bookkeeping) must be carried through the two
    places that *reconstruct* logical messages — the DGT chunk
    constructor (``DgtSender.split``) and the reassembly constructor
    (``DgtReassembler.accept``) — and must be unpacked by
    ``_unpack_hdr``.  This is the drift guard: add a new header field
    (the way ``policy_epoch`` and ``boot`` were added) and the checker
    fails until the chunk/reassembly tables carry it too.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from geomx_tpu.analysis.core import Checker, Finding, Project, SourceFile

#: enum -> (defining module rel, receiver modules that must dispatch it)
ENUMS = {
    "Control": ("geomx_tpu/transport/message.py", (
        "geomx_tpu/transport/van.py", "geomx_tpu/kvstore/server.py",
        "geomx_tpu/kvstore/client.py", "geomx_tpu/kvstore/sim.py",
        "geomx_tpu/kvstore/eviction.py", "geomx_tpu/ps/postoffice.py",
        "geomx_tpu/serve/replica.py", "geomx_tpu/obs/flight.py",
        "geomx_tpu/sched/tsengine.py", "geomx_tpu/sched/ts_push.py",
    )),
    "Ctrl": ("geomx_tpu/kvstore/common.py", (
        "geomx_tpu/kvstore/server.py", "geomx_tpu/serve/replica.py",
        "geomx_tpu/obs/collector.py", "geomx_tpu/obs/state.py",
        "geomx_tpu/trace/collector.py",
    )),
    "Cmd": ("geomx_tpu/kvstore/common.py", (
        "geomx_tpu/kvstore/server.py", "geomx_tpu/serve/replica.py",
        "geomx_tpu/kvstore/replication.py",
    )),
    "FlightEv": ("geomx_tpu/obs/flight.py", ()),  # record-site rule
}

#: _pack_hdr fields that are per-chunk / transport mechanics — the DGT
#: constructors set them per chunk (or the van stamps them at send), so
#: they are exempt from the logical-message field tables
MECHANICAL = frozenset({
    "control", "domain", "first_key", "seq", "seq_begin", "seq_end",
    "total_bytes", "channel", "val_bytes", "msg_sig",
})

#: the constructors that must carry every logical header field
FIELD_TABLES = (
    ("geomx_tpu/transport/dgt.py", "DgtSender.split",
     "DGT chunk constructor"),
    ("geomx_tpu/transport/dgt.py", "DgtReassembler.accept",
     "DGT reassembly constructor"),
)


class WireProtocol(Checker):
    name = "wire-protocol"
    description = ("protocol enum values unique + dispatched; Message "
                   "header fields carried by the DGT chunk/reassembly "
                   "field tables")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for enum_name, (def_rel, receivers) in ENUMS.items():
            findings.extend(self._check_enum(project, enum_name, def_rel,
                                             receivers))
        findings.extend(self._check_field_tables(project))
        return findings

    # -- enums -------------------------------------------------------------
    def _enum_members(self, sf: SourceFile, enum_name: str
                      ) -> List[Tuple[str, Optional[int], int]]:
        out: List[Tuple[str, Optional[int], int]] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == enum_name):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    val = None
                    if isinstance(stmt.value, ast.Constant) \
                            and isinstance(stmt.value.value, int):
                        val = stmt.value.value
                    out.append((stmt.targets[0].id, val, stmt.lineno))
        return out

    def _check_enum(self, project: Project, enum_name: str, def_rel: str,
                    receivers: Tuple[str, ...]) -> List[Finding]:
        findings: List[Finding] = []
        sf = project.by_rel.get(def_rel)
        if sf is None:
            return findings  # fixture projects carry only what they test
        members = self._enum_members(sf, enum_name)
        if not members:
            return findings
        by_val: Dict[int, List[str]] = {}
        for name, val, line in members:
            if val is not None:
                by_val.setdefault(val, []).append(name)
        for val, names in sorted(by_val.items()):
            if len(names) > 1:
                findings.append(self.finding(
                    def_rel, members[0][2], enum_name,
                    f"dup:{val}",
                    f"{enum_name} value {val} assigned to multiple "
                    f"members {names} — IntEnum silently aliases them "
                    "and the wire cannot distinguish the heads"))
        for name, _, line in members:
            pat = re.compile(rf"\b{enum_name}\.{name}\b")
            if enum_name == "FlightEv":
                # recorded somewhere (possibly via a helper inside
                # flight.py itself), or referenced outside the defining
                # module (e.g. picked by a ternary at the record site)
                rec = re.compile(rf"record\(\s*FlightEv\.{name}\b")
                recorded = any(rec.search(f.text) for f in project.files)
                outside_ref = any(f.rel != def_rel and pat.search(f.text)
                                  for f in project.files)
                if not recorded and not outside_ref:
                    findings.append(self.finding(
                        def_rel, line, enum_name, f"norecord:{name}",
                        f"FlightEv.{name} is never recorded anywhere — "
                        "a dead event code the postmortem can never "
                        "see"))
                continue
            outside = [f.rel for f in project.files
                       if f.rel != def_rel and pat.search(f.text)]
            if not outside:
                findings.append(self.finding(
                    def_rel, line, enum_name, f"unused:{name}",
                    f"{enum_name}.{name} is never referenced outside "
                    f"{def_rel} — a protocol head nobody sends or "
                    "handles"))
                continue
            wanted = [r for r in receivers if r in project.by_rel]
            if wanted and not any(r in outside for r in wanted):
                findings.append(self.finding(
                    def_rel, line, enum_name, f"undispatched:{name}",
                    f"{enum_name}.{name} has no reference in any "
                    f"receiver module ({', '.join(wanted)}) — senders "
                    "exist but nothing dispatches it"))
        return findings

    # -- Message header field tables ---------------------------------------
    def _header_fields(self, sf: SourceFile) -> Tuple[Set[str], Set[str]]:
        """(packed self.<field> names from _pack_hdr, dict keys produced
        by _unpack_hdr)."""
        packed: Set[str] = set()
        unpacked: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "_pack_hdr":
                for n in ast.walk(node):
                    if isinstance(n, ast.Attribute) \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self":
                        if n.attr not in ("_HDR",):
                            packed.add(n.attr)
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "_unpack_hdr":
                for n in ast.walk(node):
                    if isinstance(n, ast.Call):
                        fname = (n.func.id
                                 if isinstance(n.func, ast.Name) else "")
                        if fname == "dict":
                            unpacked.update(kw.arg for kw in n.keywords
                                            if kw.arg)
        return packed, unpacked

    def _check_field_tables(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        msg_sf = project.by_rel.get("geomx_tpu/transport/message.py")
        if msg_sf is None:
            return findings
        packed, unpacked = self._header_fields(msg_sf)
        if not packed:
            return findings
        # flags are packed as one word and unpacked as four bools
        flag_fields = {"request", "push", "pull", "sampled"}
        logical = (packed | flag_fields) - MECHANICAL
        missing_unpack = logical - unpacked - {"flags"}
        for f in sorted(missing_unpack):
            findings.append(self.finding(
                "geomx_tpu/transport/message.py", 1, "Message._unpack_hdr",
                f"unpack:{f}",
                f"header field {f!r} is packed by _pack_hdr but never "
                "restored by _unpack_hdr — it dies at the first TCP "
                "hop"))
        # the two DGT constructors must carry every logical field
        #  (minus flags-word internals that ride as separate kwargs)
        required = logical - {"flags"}
        dgt_sf = project.by_rel.get("geomx_tpu/transport/dgt.py")
        if dgt_sf is None:
            return findings
        for rel, qual, label in FIELD_TABLES:
            sf = project.by_rel.get(rel)
            if sf is None:
                continue
            kwargs = self._message_ctor_kwargs(sf, qual)
            if kwargs is None:
                findings.append(self.finding(
                    rel, 1, qual, "ctor-missing",
                    f"{label}: no Message(...) constructor found in "
                    f"{qual} — the field-table audit has nothing to "
                    "check"))
                continue
            got, line = kwargs
            for f in sorted(required - got):
                findings.append(self.finding(
                    rel, line, qual, f"field:{f}",
                    f"{label} does not carry Message.{f} — a chunked/"
                    "reassembled message silently loses it (the class "
                    "of bug that breaks replay dedup and trace "
                    "correlation across DGT)"))
        return findings

    def _message_ctor_kwargs(self, sf: SourceFile, qual: str
                             ) -> Optional[Tuple[Set[str], int]]:
        """Union of kwarg names over Message(...) calls plus attribute
        assignments (``chunk.keys = ...``) inside one function."""
        target = None
        for fn in sf.functions:
            if fn.qualname == qual:
                target = fn
                break
        if target is None or isinstance(target.node, ast.Lambda):
            return None
        got: Set[str] = set()
        line = target.node.lineno
        found = False
        assigned_names: Set[str] = set()
        for n in ast.walk(target.node):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "Message":
                found = True
                line = n.lineno
                got.update(kw.arg for kw in n.keywords if kw.arg)
            if isinstance(n, ast.Assign):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name):
                        assigned_names.add(tgt.attr)
        if not found:
            return None
        return got | assigned_names, line
