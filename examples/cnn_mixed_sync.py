#!/usr/bin/env python
"""Reference example-file parity: cnn_mixed_sync.py == cnn.py --sync mixed --optimizer dcasgd
(ref: examples/cnn_mixed_sync.py in the reference)."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _wrapper import run

if __name__ == "__main__":
    sys.exit(run("--sync mixed --optimizer dcasgd"))
