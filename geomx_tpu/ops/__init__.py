from geomx_tpu.ops.quantize import (  # noqa: F401
    quantize_2bit_tpu, dequantize_2bit_tpu, dgc_update_tpu,
)
