"""Server-state checkpoint/restore and wire-format snapshots.

The reference keeps server model state only in RAM and supports
client-side optimizer-state saves that are explicitly unsupported for
distributed updaters (ref: python/mxnet/kvstore.py:566-591;
kvstore_dist_server.h:1923 store_ map) — SURVEY.md §7 flags server-side
checkpointing as an improvement to build.  Format: a single .npz holding
the weight slabs keyed by ps-key plus pickled optimizer state, written
atomically (tmp + rename) so a crash mid-save never corrupts the last
good checkpoint.

``dumps_server_state`` / ``loads_server_state`` expose the same slab
format as bytes — the hot-standby replication stream ships exactly what
a checkpoint would hold, over the wire instead of disk, so the standby's
restore path and the crash-restart restore path stay one code path.

The pickled optimizer is ALWAYS the host-numpy ``ServerOptimizer``:
a server running the device-resident optimizer stage
(kvstore/jax_backend.py) exports its trajectory through
``GlobalServer._export_opt_locked()`` before any state reaches this
module, and re-imports on restore — the slab format is engine-agnostic
by construction, so checkpoints round-trip between numpy and device
servers in both directions.
"""

from __future__ import annotations

import io
import os
import pickle
import struct
from typing import Dict, List

import numpy as np

from geomx_tpu.transport.message import wire_checksum
from geomx_tpu.utils.io import atomic_write

# Verified-slab format (GEOMX_INTEGRITY_CKPT): the npz blob is wrapped
# in a magic + format-version + whole-blob CRC header, and the payload
# additionally carries a per-slab CRC table ("__crc__") so a restore
# can pinpoint WHICH slab rotted.  Legacy blobs (bare npz, "PK" zip
# magic) load unchanged; the stamp is opt-in so a mixed-version fleet's
# replication stream stays readable both ways.
CKPT_INTEGRITY = (os.environ.get("GEOMX_INTEGRITY_CKPT", "")
                  .strip().lower() in ("1", "true", "yes", "on"))
_CKPT_MAGIC = b"GXCK"
_CKPT_VERSION = 1
_CKPT_HDR = struct.Struct("<HI")  # version, crc32 of the npz blob


class CheckpointCorruption(ValueError):
    """A stamped server-state blob failed verification (bad CRC,
    truncation, or an unknown format version).  Restore paths catch
    this and fall back to the previous generation; a standby rejects
    the snapshot and keeps the one it has."""


def dumps_server_state(store: Dict[int, np.ndarray],
                       optimizer_state: dict, meta: dict,
                       integrity: bool = None) -> bytes:
    payload: Dict[str, np.ndarray] = {
        f"k{k}": v for k, v in store.items()
    }
    payload["__opt__"] = np.frombuffer(
        pickle.dumps(optimizer_state, protocol=4), dtype=np.uint8)
    payload["__meta__"] = np.frombuffer(
        pickle.dumps(meta, protocol=4), dtype=np.uint8)
    if integrity is None:
        integrity = CKPT_INTEGRITY
    if integrity:
        crcs = {name: wire_checksum(np.ascontiguousarray(v).tobytes())
                for name, v in payload.items()}
        payload["__crc__"] = np.frombuffer(
            pickle.dumps(crcs, protocol=4), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    blob = buf.getvalue()
    if not integrity:
        return blob
    return (_CKPT_MAGIC
            + _CKPT_HDR.pack(_CKPT_VERSION, wire_checksum(blob)) + blob)


def loads_server_state(data: bytes):
    """Returns (store, optimizer_state, meta).  A stamped blob is
    verified end to end first (whole-blob CRC, then per-slab CRCs);
    any mismatch raises :class:`CheckpointCorruption` — including npz/
    pickle parse failures past a valid-looking stamp, so callers need
    exactly one except clause on the restore path."""
    stamped = data[:4] == _CKPT_MAGIC
    if stamped:
        if len(data) < 4 + _CKPT_HDR.size:
            raise CheckpointCorruption("truncated checkpoint header")
        version, crc = _CKPT_HDR.unpack_from(data, 4)
        if version != _CKPT_VERSION:
            raise CheckpointCorruption(
                f"unknown checkpoint format version {version}")
        data = data[4 + _CKPT_HDR.size:]
        if wire_checksum(data) != crc:
            raise CheckpointCorruption("checkpoint blob CRC mismatch")
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            store = {int(name[1:]): z[name] for name in z.files
                     if name.startswith("k")}
            opt = pickle.loads(z["__opt__"].tobytes())
            meta = pickle.loads(z["__meta__"].tobytes())
            crcs = (pickle.loads(z["__crc__"].tobytes())
                    if "__crc__" in z.files else None)
    except CheckpointCorruption:
        raise
    except Exception as e:
        if stamped:
            # the outer CRC passed, so this is a writer bug or an
            # unsupported payload — surface it as corruption anyway:
            # the restore path's job is falling back, not crashing
            raise CheckpointCorruption(f"stamped blob unparseable: {e}")
        raise
    if crcs is not None:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            for name in z.files:
                if name == "__crc__":
                    continue
                want = crcs.get(name)
                got = wire_checksum(
                    np.ascontiguousarray(z[name]).tobytes())
                if want is None or got != want:
                    raise CheckpointCorruption(
                        f"slab '{name}' CRC mismatch")
    return store, opt, meta


def save_server_state(path: str, store: Dict[int, np.ndarray],
                      optimizer_state: dict, meta: dict,
                      integrity: bool = None) -> None:
    blob = dumps_server_state(store, optimizer_state, meta,
                              integrity=integrity)
    with atomic_write(path) as f:
        f.write(blob)


def load_server_state(path: str):
    """Returns (store, optimizer_state, meta)."""
    with open(path, "rb") as f:
        return loads_server_state(f.read())


# ---- N-generation retention -------------------------------------------------
def rotate_generations(path: str, keep: int) -> None:
    """Shift ``path`` → ``path.1`` → … → ``path.{keep-1}`` before a new
    write lands at ``path`` (the oldest generation falls off the end).
    ``keep <= 1`` keeps today's single-file behavior."""
    for i in range(max(1, keep) - 1, 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i}")


def restore_candidates(path: str) -> List[str]:
    """Existing generations, newest first: ``path``, ``path.1``, …"""
    out = [path] if os.path.exists(path) else []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        out.append(f"{path}.{i}")
        i += 1
    return out
