"""Event-driven transport core (ISSUE 12): reactor loops, timer wheel,
lightweight-party mode.

Pins the properties the O(100)-party harness rests on:

1.  ``SerialChannel`` preserves per-channel FIFO order on the shared
    pool — the ordering guarantee the per-node recv/customer threads
    provided;
2.  the reactor ``TcpFabric`` keeps the wire-v2 zero-copy contract
    (decoded arrays alias the receive buffer, adopt uncopied) and the
    UDP lossy channel;
3.  lightweight simulations are BITWISE equal to the threads transport
    (integer grads → exact sums) while running O(1) threads in node
    count, with heartbeat/resend/monitor loops absorbed by the timer
    wheel (no per-node timer threads);
4.  both transports return the process to its thread baseline after
    ``Simulation.shutdown()`` (the thread-leak guard satellite);
5.  the reactor pressure gauges (``process_threads`` /
    ``reactor_loop_lag_ms`` / ``reactor_fds``) land in the flight
    recorder and the system-metrics registry.

The 128-party / 512-worker soak is marked ``scale`` (and ``slow``) so
it stays out of tier-1 but runs on demand: ``pytest -m scale``.
"""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation
from geomx_tpu.transport.reactor import (Periodic, Reactor,
                                         resolve_transport)


def free_base_port(span: int = 16):
    """A base port with ``span`` consecutive free ports, outside the
    kernel ephemeral range (see tests/test_tcp.py for the rationale)."""
    import random
    import socket

    for _ in range(200):
        base = random.randrange(18000, 28000)
        try:
            socks = []
            try:
                for i in range(span):
                    s = socket.socket()
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind(("127.0.0.1", base + i))
                    socks.append(s)
            finally:
                for s in socks:
                    s.close()
            return base
        except OSError:
            continue
    raise RuntimeError("no free port span found")


# ---------------------------------------------------------------------------
# reactor primitives
# ---------------------------------------------------------------------------

def test_resolve_transport_precedence(monkeypatch):
    monkeypatch.delenv("GEOMX_TRANSPORT", raising=False)
    assert resolve_transport(None) == "reactor"  # the ISSUE 20 default
    monkeypatch.setenv("GEOMX_TRANSPORT", "threads")  # escape hatch
    assert resolve_transport(None) == "threads"
    monkeypatch.setenv("GEOMX_TRANSPORT", "reactor")
    assert resolve_transport(None) == "reactor"
    # an explicit Config field wins over the env
    cfg = Config(topology=Topology(), transport="threads")
    assert resolve_transport(cfg) == "threads"
    monkeypatch.setenv("GEOMX_TRANSPORT", "bogus")
    with pytest.raises(ValueError):
        resolve_transport(None)
    with pytest.raises(ValueError):
        Config(topology=Topology(), transport="bogus")


def test_serial_channel_preserves_fifo_under_concurrency():
    """N producers race one channel: the consumer must observe every
    producer's items in that producer's put order (the per-node message
    order the dedicated recv thread guaranteed)."""
    r = Reactor(loops=1, workers=4, name="t-reactor-fifo")
    try:
        got = []
        mu = threading.Lock()

        def consume(item):
            with mu:
                got.append(item)

        chan = r.channel(consume, name="t-chan")
        n_producers, per = 8, 200

        def produce(pid):
            for i in range(per):
                chan.put((pid, i))

        ts = [threading.Thread(target=produce, args=(p,))
              for p in range(n_producers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with mu:
                if len(got) == n_producers * per:
                    break
            time.sleep(0.01)
        with mu:
            assert len(got) == n_producers * per
            seen = {p: -1 for p in range(n_producers)}
            for pid, i in got:
                assert i == seen[pid] + 1, (
                    f"producer {pid} reordered: {i} after {seen[pid]}")
                seen[pid] = i
        chan.close()
        chan.put(("late", 0))  # closed channel drops silently
    finally:
        r.stop()


def test_timer_wheel_fires_and_cancels():
    r = Reactor(loops=1, workers=2, name="t-reactor-timer")
    try:
        fired = []
        task = r.call_every(0.05, lambda: fired.append(time.monotonic()),
                            name="t-tick")
        deadline = time.monotonic() + 5
        while len(fired) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(fired) >= 3, "repeating timer never fired"
        task.cancel()
        time.sleep(0.15)
        n = len(fired)
        time.sleep(0.2)
        assert len(fired) == n, "cancelled timer kept firing"
        # Periodic helper on the same wheel
        hits = []
        p = Periodic(0.05, lambda: hits.append(1), name="t-per", reactor=r)
        deadline = time.monotonic() + 5
        while not hits and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hits, "Periodic-on-reactor never fired"
        p.stop()
        assert r.loop_lag_ms() >= 0.0
        assert isinstance(r.fd_counts(), list)
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# reactor TcpFabric: wire parity + zero-copy + UDP
# ---------------------------------------------------------------------------

def _tcp_pair(base_port):
    """Two reactor-mode fabrics over one plan (a real wire between
    them — same-fabric delivery would take the in-proc shortcut)."""
    from geomx_tpu.transport import Van
    from geomx_tpu.transport.tcp import TcpFabric, default_address_plan

    topo = Topology(num_parties=1, workers_per_party=1)
    plan = default_address_plan(topo, base_port=base_port)
    cfg = Config(topology=topo, transport="reactor")
    fab_a = TcpFabric(dict(plan), config=cfg)
    fab_b = TcpFabric(dict(plan), config=cfg)
    a, b = topo.workers(0)[0], topo.server(0)
    return cfg, fab_a, fab_b, Van(a, fab_a, config=cfg), \
        Van(b, fab_b, config=cfg), a, b


def test_tcp_reactor_roundtrip_zero_copy():
    """The PR 5 zero-copy contract survives the reactor recv state
    machine: decoded vals are writeable views over the receive buffer
    and the server adopt gate takes them WITHOUT a copy."""
    from geomx_tpu.kvstore.server import _adopt_or_copy
    from geomx_tpu.transport.message import Domain, Message

    cfg, fab_a, fab_b, van_a, van_b, a, b = _tcp_pair(free_base_port())
    try:
        got, ev = [], threading.Event()
        van_a.start(lambda m: None)
        van_b.start(lambda m: (got.append(m), ev.set()))
        vals = np.arange(1_000_000, dtype=np.float32)
        van_a.send(Message(recipient=b, domain=Domain.LOCAL,
                           keys=np.array([7], np.int64), vals=vals,
                           lens=np.array([vals.size], np.int64),
                           push=True, request=True))
        assert ev.wait(15), "reactor fabric never delivered"
        m = got[0]
        np.testing.assert_array_equal(m.vals, vals)
        assert m.donated, "wire decode lost the donated flag"
        assert m.vals.flags.writeable
        assert m.vals.base is not None, "decode copied off the buffer"
        assert m.vals.ctypes.data % 8 == 0, "payload lost its alignment"
        adopted = _adopt_or_copy(m.vals, m.donated)
        assert adopted is m.vals, "adopt gate copied a donated wire view"
    finally:
        van_a.stop()
        van_b.stop()
        fab_a.shutdown()
        fab_b.shutdown()


def test_tcp_reactor_many_messages_and_udp():
    """Ordering + completeness over the framed stream (200 messages
    through the recv state machine) and the lossy UDP channel."""
    from geomx_tpu.transport.message import Domain, Message

    cfg, fab_a, fab_b, van_a, van_b, a, b = _tcp_pair(free_base_port())
    try:
        seen, done = [], threading.Event()

        def on_b(m):
            seen.append(int(m.keys[0]))
            if len(seen) >= 200:
                done.set()

        van_a.start(lambda m: None)
        van_b.start(on_b)
        for i in range(200):
            van_a.send(Message(recipient=b, domain=Domain.LOCAL,
                               keys=np.array([i], np.int64),
                               vals=np.full(64, i, np.float32),
                               lens=np.array([64], np.int64),
                               push=True, request=True))
        assert done.wait(20), f"only {len(seen)}/200 frames arrived"
        assert seen == list(range(200)), "stream reordered or torn"
        # lossy channel: datagram-sized payload rides UDP end to end
        got_udp = threading.Event()
        van_a.stop()
        van_a.start(lambda m: got_udp.set())
        van_b.send(Message(recipient=a, domain=Domain.LOCAL, channel=1,
                           keys=np.array([1], np.int64),
                           vals=np.ones(64, np.float32),
                           lens=np.array([64], np.int64),
                           push=True, request=True))
        assert got_udp.wait(10), "UDP lossy channel never delivered"
        assert fab_b.udp_datagrams_sent >= 1
        assert fab_a.udp_datagrams_recv >= 1
    finally:
        van_a.stop()
        van_b.stop()
        fab_a.shutdown()
        fab_b.shutdown()


# ---------------------------------------------------------------------------
# lightweight-party mode: parity + thread elision
# ---------------------------------------------------------------------------

def _fsa_weights(lightweight: bool, deterministic: bool = False,
                 rounds: int = 3):
    """One small FSA run; integer-valued grads + power-of-two lr make
    every merge/optimizer op exact, so transports must agree BITWISE."""
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=2),
                 deterministic=deterministic, enable_flight=False)
    sim = Simulation(cfg, lightweight=lightweight)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(256, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.5})
        g = np.full(256, 2.0, np.float32)
        out = None
        for _ in range(rounds):
            for w in ws:
                w.push(0, g)
            for w in ws:
                out = w.pull_sync(0)
                w.wait_all()
        return np.array(out, copy=True)
    finally:
        sim.shutdown()


def test_lightweight_fsa_bitwise_parity_vs_threads():
    w_threads = _fsa_weights(lightweight=False)
    w_light = _fsa_weights(lightweight=True)
    assert w_threads.dtype == w_light.dtype
    assert np.array_equal(w_threads, w_light), (
        "lightweight mode diverged from the threads transport")


def test_deterministic_bit_identical_across_transports():
    """Deterministic mode (serial fabric) must stay bit-identical
    whatever the transport knob says — the reactor path defers to the
    NaiveEngine-analog dispatcher."""
    a = _fsa_weights(lightweight=False, deterministic=True)
    b = _fsa_weights(lightweight=True, deterministic=True)
    assert np.array_equal(a, b)


def test_lightweight_thread_count_is_o1_in_party_count():
    """The tentpole claim: per-process thread count bounded by the
    reactor pool, not node count.  8 parties x 2 workers = 35 nodes;
    the thread-per-endpoint harness spends ~10 threads per party on
    this topology, lightweight mode must not grow with parties."""
    before = threading.active_count()
    cfg = Config(topology=Topology(num_parties=8, workers_per_party=2),
                 enable_flight=False)
    sim = Simulation(cfg, lightweight=True)
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(64, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.5})
        for w in ws:
            w.push(0, np.ones(64, np.float32))
        for w in ws:
            w.pull_sync(0)
            w.wait_all()
        grown = threading.active_count() - before
        # reactor loops + lazily-spawned pool workers, NOT ~80 per-node
        # threads (the legacy harness at this topology)
        budget = sim.reactor.loops + sim.reactor.workers + 4
        assert grown <= budget, (
            f"lightweight sim grew {grown} threads "
            f"(> reactor budget {budget}) — per-node stacks are back")
    finally:
        sim.shutdown()


def test_timer_wheel_absorbs_heartbeat_and_resend_threads():
    """With heartbeats + the resender on — plus a bandwidth-shaped
    fabric (priority send queues) and the intra-party TS overlay
    (dissemination clients) — a lightweight sim must run ZERO per-node
    timer threads (heartbeat-* / van-resend-*), zero per-node dispatch
    threads (van-recv-* / customer-*), and zero per-node drain threads
    (van-send-* / ts-dissem-*, the two PR 12 left behind) — they all
    live on the shared wheel/pool — while heartbeats still arrive at
    the schedulers."""
    from geomx_tpu.transport.van import FaultPolicy

    before = set(threading.enumerate())  # earlier tests' stop-flagged
    #                                      loops may still be winding down
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=2),
                 heartbeat_interval_s=0.05, resend_timeout_ms=200,
                 enable_intra_ts=True, enable_flight=False)
    sim = Simulation(cfg, lightweight=True,
                     fault=FaultPolicy(wan_bandwidth_bps=1e12))
    try:
        banned = ("heartbeat-", "van-resend-", "van-recv-", "customer-",
                  "van-send-", "ts-dissem-",
                  "WorkerEvictionMonitor", "LocalServerRecoveryMonitor",
                  "metrics-pump-")
        names = [t.name for t in threading.enumerate() if t not in before]
        offenders = [n for n in names
                     if any(n.startswith(b) for b in banned)]
        assert not offenders, f"per-node loops survived: {offenders}"
        sched_po = sim.offices[str(sim.topology.scheduler(0))]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with sched_po._lock:
                if len(sched_po._heartbeats) >= 3:  # 2 workers + server
                    break
            time.sleep(0.02)
        with sched_po._lock:
            assert len(sched_po._heartbeats) >= 3, (
                "timer-wheel heartbeats never reached the scheduler")
    finally:
        sim.shutdown()


def test_reactor_pressure_gauges_registered():
    """process_threads / reactor_loop_lag_ms / reactor_fds land in the
    flight recorder's pressure sweep AND the system-metrics registry
    (the press[...] console column and the pump read them back)."""
    from geomx_tpu.utils.metrics import system_snapshot

    cfg = Config(topology=Topology(num_parties=1, workers_per_party=1))
    sim = Simulation(cfg, lightweight=True)
    try:
        po = sim.offices[str(sim.topology.global_scheduler())]
        assert po.flight is not None
        readings = po.flight.sample_pressure()
        assert readings.get("process_threads", 0) >= 1
        assert "reactor_loop_lag_ms" in readings
        assert readings.get("reactor_fds") is not None
        snap = system_snapshot(prefix=f"{po.node}.", skip_unset=True)
        assert f"{po.node}.process_threads" in snap
        assert f"{po.node}.reactor_fds" in snap
    finally:
        sim.shutdown()


def test_legacy_path_has_no_reactor_gauges():
    """The threads transport must not grow reactor gauges (disabled
    path = the pre-reactor recorder surface, exactly)."""
    cfg = Config(topology=Topology(num_parties=1, workers_per_party=1))
    sim = Simulation(cfg, lightweight=False)
    try:
        po = sim.offices[str(sim.topology.global_scheduler())]
        readings = po.flight.sample_pressure()
        assert "reactor_loop_lag_ms" not in readings
        assert "reactor_fds" not in readings
        assert "process_threads" in readings  # useful everywhere
    finally:
        sim.shutdown()


# ---------------------------------------------------------------------------
# thread-leak guard (satellite): both transports return to baseline
# ---------------------------------------------------------------------------

def _leak_probe_sim(lightweight: bool):
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=2),
                 heartbeat_interval_s=0.2, resend_timeout_ms=500,
                 enable_flight=False)
    sim = Simulation(cfg, lightweight=lightweight)
    ws = sim.all_workers()
    for w in ws:
        w.init(0, np.zeros(64, np.float32))
    ws[0].set_optimizer({"type": "sgd", "lr": 0.5})
    for w in ws:
        w.push(0, np.ones(64, np.float32))
    for w in ws:
        w.pull_sync(0)
        w.wait_all()
    sim.shutdown()


def test_thread_leak_guard_legacy_transport(thread_leak_guard):
    _leak_probe_sim(lightweight=False)


def test_thread_leak_guard_lightweight_transport(thread_leak_guard):
    _leak_probe_sim(lightweight=True)


def test_thread_leak_guard_tcp_reactor_fabric(thread_leak_guard):
    """Reactor TCP fabric shutdown unregisters every fd and leaves no
    per-connection threads behind (there were none to begin with)."""
    from geomx_tpu.transport import Van
    from geomx_tpu.transport.message import Domain, Message
    from geomx_tpu.transport.tcp import TcpFabric, default_address_plan

    topo = Topology(num_parties=1, workers_per_party=1)
    plan = default_address_plan(topo, base_port=free_base_port())
    cfg = Config(topology=topo, transport="reactor")
    fab = TcpFabric(plan, config=cfg)
    a, b = topo.workers(0)[0], topo.server(0)
    van_a, van_b = Van(a, fab, config=cfg), Van(b, fab, config=cfg)
    ev = threading.Event()
    van_a.start(lambda m: None)
    van_b.start(lambda m: ev.set())
    van_a.send(Message(recipient=b, domain=Domain.LOCAL,
                       keys=np.array([1], np.int64),
                       vals=np.ones(8, np.float32),
                       lens=np.array([8], np.int64), push=True,
                       request=True))
    assert ev.wait(10)
    before_fds = fab.reactor.fd_count()
    assert before_fds >= 2  # 1 listener + 1 udp per registered node
    van_a.stop()
    van_b.stop()
    fab.shutdown()
    deadline = time.monotonic() + 5
    while fab.reactor.fd_count() > 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert fab.reactor.fd_count() == 0, (
        "fabric shutdown left fds registered on the shared reactor")


# ---------------------------------------------------------------------------
# the O(100)-party soak (out of tier-1: pytest -m scale)
# ---------------------------------------------------------------------------

@pytest.mark.scale
@pytest.mark.slow
def test_128_party_512_worker_soak():
    """Acceptance: a 128-party / 512-worker lightweight topology
    completes a multi-round FSA run on one host with O(1) threads."""
    before = threading.active_count()
    cfg = Config(topology=Topology(num_parties=128, workers_per_party=4),
                 enable_flight=False)
    sim = Simulation(cfg, lightweight=True)
    try:
        ws = sim.all_workers()
        assert len(ws) == 512
        for w in ws:
            w.init(0, np.zeros(4096, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.5})
        g = np.full(4096, 2.0, np.float32)
        out = None
        for _ in range(3):
            for w in ws:
                w.push(0, g)
            for w in ws:
                out = w.pull_sync(0)
                w.wait_all()
        # 3 rounds of exact integer math: -lr * mean_grad * rounds
        assert out is not None and np.all(out == out[0])
        grown = threading.active_count() - before
        budget = sim.reactor.loops + sim.reactor.workers + 8
        assert grown <= budget, (
            f"{grown} threads at 128 parties (budget {budget}) — "
            "thread count is not O(1) in party count")
    finally:
        sim.shutdown()
