#!/usr/bin/env python
"""ESync demo: cnn_esync.py == cnn.py --esync (the reference lists
ESync as to-be-integrated, ref: README.md:45; integrated here — the
party's state server balances per-worker local step counts)."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _wrapper import run

if __name__ == "__main__":
    sys.exit(run("--esync"))
