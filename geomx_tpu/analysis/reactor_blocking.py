"""Checker: no blocking primitives reachable from reactor contexts.

Under ``GEOMX_TRANSPORT=reactor`` (transport/reactor.py) every node's
inbound dispatch, timers and socket callbacks run on a small shared
pool — a handler that parks its thread stalls *other nodes'* traffic,
and at O(100) parties that is a cluster-wide wedge, not a local bug
(the PR 13 warm-boot wedge was exactly a blocking ``send_cmd`` inside a
handler).  This checker finds the reactor entry points statically and
walks the call graph a bounded depth looking for blocking primitives.

Roots (all discovered from the AST, no runtime needed):

- *strict* contexts — must never block at all:
  ``reactor.channel(cb)`` callbacks (``SerialChannel`` dispatch — in
  lightweight mode this is every Customer handler), ``call_later`` fns
  (they run ON the selector loop thread), ``register(read_cb=/
  write_cb=)`` socket callbacks (also loop-thread), ``Customer(...)``
  handler arguments, and any function assigned to a ``*_handler`` /
  ``*_cb`` / ``*_hook`` attribute (the codebase's callback idiom).
- *periodic* contexts — may block briefly on a bounded timeout, never
  unboundedly: ``call_every`` / ``Periodic`` tick functions (they run
  on the worker pool; the reactor skips overlapped ticks).

Blocking primitives::

    sleep            time.sleep(...)
    wait-unbounded   .wait() / .wait(timeout=None)
    wait-default     .wait(x) with no timeout= (Customer.wait's default
                     is 120 s — two minutes of a shared pool worker)
    queue-get        .get() with no args (queue.get blocks; dict.get
                     always takes a key, so zero-arg get IS a queue)
    thread-join      .join() with no timeout
    send-cmd         send_cmd(...) without wait=False (the default
                     wait=True parks in Customer.wait; pass wait=False
                     and poll, or hand the work to a thread)
    wait-true        any call with an explicit wait=True kwarg
    drain            ShardExecutor.drain() (waits on the merge lanes)
    future-result    .result() with no timeout

Strict contexts flag all of them; periodic contexts flag only the
unbounded ones (sleep, wait-unbounded, queue-get, thread-join,
send-cmd, wait-true).  Handing work to ``threading.Thread(target=...)``
is the sanctioned escape hatch and is naturally invisible here — the
graph only follows *calls*, and a Thread target is not called by its
spawner.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from geomx_tpu.analysis.core import (CallGraph, CallSite, Checker, Finding,
                                     FunctionInfo, Project, _attr_chain)

_HANDLER_ATTR_SUFFIXES = ("_handler", "_cb", "_hook", "handler")

#: codes flagged even in periodic (bounded-tick) contexts
_UNBOUNDED = frozenset({"sleep", "wait-unbounded", "queue-get",
                        "thread-join", "send-cmd", "wait-true"})

MAX_DEPTH = 8


def _timeout_kw(call: CallSite) -> Optional[ast.expr]:
    return call.keyword("timeout")


def classify_blocking(call: CallSite) -> Optional[str]:
    """The blocking-primitive code for one call site, or None."""
    name, recv = call.name, call.recv
    if name == "sleep" and recv == "time":
        return "sleep"
    if name in ("wait", "wait_for"):
        to = _timeout_kw(call)
        if to is not None:
            if isinstance(to, ast.Constant) and to.value is None:
                return "wait-unbounded"
            return None  # explicitly bounded
        if call.num_pos_args == 0:
            return "wait-unbounded"
        if call.num_pos_args >= 2:
            return None  # wait(x, timeout) / wait_for(pred, t) positional
        # one positional arg: Event.wait(t) is bounded by it, but
        # Customer.wait(ts) falls back to the 120 s default — the exact
        # send_cmd wedge class, so the customer shape is flagged
        if call.recv is not None and "customer" in call.recv:
            return "wait-default"
        return None
    if name == "get" and call.num_pos_args == 0 and not call.node.keywords:
        return "queue-get"
    if name == "join" and call.num_pos_args == 0 \
            and not call.has_keyword("timeout"):
        # str.join always takes the iterable positionally, so a
        # zero-arg join can only be a thread/process join
        return "thread-join"
    if name == "send_cmd":
        if call.keyword_is_const("wait", False):
            return None
        return "send-cmd"
    if call.keyword_is_const("wait", True):
        return "wait-true"
    if name == "drain":
        return "drain"
    if name == "result" and call.num_pos_args == 0 \
            and not call.has_keyword("timeout"):
        return "future-result"
    return None


class ReactorBlocking(Checker):
    name = "reactor-blocking"
    description = ("no blocking primitives reachable from SerialChannel "
                   "handlers, selector callbacks, or timer ticks")

    def run(self, project: Project) -> List[Finding]:
        graph = CallGraph(project)
        strict_roots, periodic_roots = self._roots(project, graph)
        findings: List[Finding] = []
        seen: Set[str] = set()
        strict_reach = graph.reachable(
            sorted(strict_roots.values(), key=lambda r: r.source_id()),
            max_depth=MAX_DEPTH)
        periodic_reach = graph.reachable(
            sorted(periodic_roots.values(), key=lambda r: r.source_id()),
            max_depth=MAX_DEPTH)
        for reach, mode in ((strict_reach, "strict"),
                            (periodic_reach, "periodic")):
            for fn, chain in reach.values():
                # a function reached by BOTH modes reports under strict
                # only (the superset rule set)
                if mode == "periodic" and id(fn) in strict_reach:
                    continue
                for call in fn.calls:
                    code = classify_blocking(call)
                    if code is None:
                        continue
                    if mode == "periodic" and code not in _UNBOUNDED:
                        continue
                    f = self.finding(
                        fn.module.rel, call.line, fn.qualname,
                        f"{code}:{call.name}",
                        f"{code}: {call.name}() can block a "
                        f"{'reactor dispatch/loop' if mode == 'strict' else 'timer-wheel tick'}"
                        f" context (via {' -> '.join(chain)})")
                    if f.key not in seen:
                        seen.add(f.key)
                        findings.append(f)
        return findings

    # -- root discovery ----------------------------------------------------
    def _roots(self, project: Project, graph: CallGraph
               ) -> Tuple[Dict[str, FunctionInfo], Dict[str, FunctionInfo]]:
        strict: Dict[str, FunctionInfo] = {}
        periodic: Dict[str, FunctionInfo] = {}

        def add(table: Dict[str, FunctionInfo],
                fns: List[FunctionInfo]) -> None:
            for fn in fns:
                table.setdefault(fn.source_id(), fn)

        for fn in project.functions:
            for call in fn.calls:
                args = call.node.args
                if call.name == "channel" and args:
                    add(strict, self._funcref(project, fn, args[0]))
                elif call.name == "call_later" and len(args) >= 2:
                    add(strict, self._funcref(project, fn, args[1]))
                elif call.name == "call_every" and len(args) >= 2:
                    add(periodic, self._funcref(project, fn, args[1]))
                elif call.name == "Periodic" and len(args) >= 2:
                    add(periodic, self._funcref(project, fn, args[1]))
                elif call.name == "Customer" and len(args) >= 3:
                    add(strict, self._funcref(project, fn, args[2]))
                elif call.name == "register":
                    for kw in call.node.keywords:
                        if kw.arg in ("read_cb", "write_cb"):
                            add(strict,
                                self._funcref(project, fn, kw.value))
            # attribute-assigned handlers: self.x_handler = self._f
            self._handler_assigns(project, fn, strict)
        return strict, periodic

    def _handler_assigns(self, project: Project, fn: FunctionInfo,
                         strict: Dict[str, FunctionInfo]) -> None:
        node = fn.node
        if isinstance(node, ast.Lambda):
            return
        for n in ast.walk(node):
            if not isinstance(n, ast.Assign):
                continue
            for tgt in n.targets:
                if not isinstance(tgt, ast.Attribute):
                    continue
                if not any(tgt.attr.endswith(s)
                           for s in _HANDLER_ATTR_SUFFIXES):
                    continue
                for ref in self._funcref(project, fn, n.value):
                    strict.setdefault(ref.source_id(), ref)

    def _funcref(self, project: Project, ctx: FunctionInfo,
                 expr: ast.expr) -> List[FunctionInfo]:
        """Resolve a callback-reference expression to project
        functions."""
        # functools.partial(f, ...) / lambda wrappers
        if isinstance(expr, ast.Call):
            fname = (expr.func.attr if isinstance(expr.func, ast.Attribute)
                     else expr.func.id if isinstance(expr.func, ast.Name)
                     else "")
            if fname == "partial" and expr.args:
                return self._funcref(project, ctx, expr.args[0])
            return []
        if isinstance(expr, ast.Lambda):
            for fn in ctx.module.functions:
                if fn.node is expr:
                    return [fn]
            return []
        chain = _attr_chain(expr)
        if chain is None:
            return []
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            if ctx.cls is None:
                return []
            return project.mro_methods(ctx.cls, parts[1])
        if len(parts) == 1:
            fn = project.module_functions.get((ctx.module.rel, parts[0]))
            if fn is not None:
                return [fn]
            # nested function of the current one
            for fn in ctx.module.functions:
                if fn.qualname == f"{ctx.qualname}.{parts[0]}":
                    return [fn]
            return []
        # foreign attr ref (obj.method): unique-name resolution
        cands = project.methods.get(parts[-1], [])
        owners = {c.cls for c in cands}
        if 0 < len(owners) <= 3:
            return list(cands)
        return []
