"""Pipeline parallelism on the FLAGSHIP transformer (VERDICT r2 item 5):
pp stages = transformer layers, composed with dp, gradients identical to
the sequential model.  Runs on the 8-device virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from geomx_tpu.models.transformer import (
    TransformerConfig, _layer_forward, _rms_norm, _single_device_attention,
    token_cross_entropy,
)
from geomx_tpu.parallel import make_mesh
from geomx_tpu.parallel.pipeline import (
    init_pp_transformer, make_pp_apply, pp_param_specs,
)

CFG = dict(vocab=64, d_model=16, n_heads=2, n_layers=4, d_ff=32,
           max_seq=32, compute_dtype=jnp.float32)


def _sequential_ref(cfg):
    """Same math as make_pp_apply, no pipeline: scan the stacked layers."""
    def block(layer, x):
        return _layer_forward(
            cfg, 0, layer, x,
            lambda q, k, v: _single_device_attention(cfg, q, k, v))[0]

    def apply(pp_params, tokens):
        B, T = tokens.shape
        cd = cfg.compute_dtype
        x = pp_params["embed"][tokens].astype(cd)
        x = x + pp_params["pos"][:T][None].astype(cd)
        x, _ = lax.scan(lambda c, p: (block(p, c), None), x,
                        pp_params["layers"])
        x = _rms_norm(x, pp_params["ln_f"])
        logits = jnp.einsum("btd,dv->btv", x, pp_params["head"].astype(cd))
        return logits.astype(jnp.float32)

    return apply


_ce = token_cross_entropy


def _tokens(b, t, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, CFG["vocab"], (b, t)),
        jnp.int32)


def test_pp_flagship_forward_matches_sequential():
    cfg = TransformerConfig(**CFG)
    mesh = make_mesh({"pp": 4})
    pp_params = init_pp_transformer(cfg, jax.random.PRNGKey(0))
    tokens = _tokens(8, 32)
    apply_pp = make_pp_apply(cfg, mesh, n_microbatches=4)
    ref = _sequential_ref(cfg)(pp_params, tokens)
    out = jax.jit(apply_pp)(pp_params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_pp_flagship_train_step_matches_sequential():
    """Loss AND gradients bit-match the unpipelined model — the schedule
    is pure reordering, so autodiff through it is the chain rule."""
    cfg = TransformerConfig(**CFG)
    mesh = make_mesh({"pp": 4})
    pp_params = init_pp_transformer(cfg, jax.random.PRNGKey(1))
    tokens = _tokens(8, 32, seed=1)
    apply_pp = make_pp_apply(cfg, mesh, n_microbatches=4)
    ref_apply = _sequential_ref(cfg)

    loss_pp, grads_pp = jax.jit(jax.value_and_grad(
        lambda p: _ce(apply_pp(p, tokens), tokens)))(pp_params)
    loss_ref, grads_ref = jax.jit(jax.value_and_grad(
        lambda p: _ce(ref_apply(p, tokens), tokens)))(pp_params)

    assert abs(float(loss_pp) - float(loss_ref)) < 1e-6
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(grads_pp),
                   key=str),
            sorted(jax.tree_util.tree_leaves_with_path(grads_ref),
                   key=str)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=str(ka))


def test_pp_dp_composition_matches_single_device():
    """pp×dp mesh: microbatch batch dim sharded over dp, layers over pp;
    output matches the single-device sequential model."""
    cfg = TransformerConfig(**CFG)
    mesh = make_mesh({"pp": 4, "dp": 2})
    pp_params = init_pp_transformer(cfg, jax.random.PRNGKey(2))
    tokens = _tokens(8, 32, seed=2)

    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pp_param_specs(pp_params),
        is_leaf=lambda x: isinstance(x, P))
    sharded = jax.device_put(pp_params, pshard)

    apply_pp = make_pp_apply(cfg, mesh, n_microbatches=2, dp_axis="dp")
    out = jax.jit(apply_pp)(sharded, tokens)
    ref = _sequential_ref(cfg)(pp_params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    # composed train step: loss AND grads must match the sequential
    # model (check_vma=False means shard_map can't verify replication —
    # a transpose that forgot the dp psum would still be finite, so
    # finiteness alone proves nothing)
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: _ce(apply_pp(p, tokens), tokens)))(sharded)
    loss_ref, grads_ref = jax.jit(jax.value_and_grad(
        lambda p: _ce(_sequential_ref(cfg)(p, tokens), tokens)))(pp_params)
    assert abs(float(loss) - float(loss_ref)) < 1e-5
    for (ka, a), (_kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(grads), key=str),
            sorted(jax.tree_util.tree_leaves_with_path(grads_ref),
                   key=str)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=str(ka))
