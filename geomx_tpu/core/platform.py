"""Honor a JAX_PLATFORMS request made through the environment.

Site customization in some deployments imports jax at interpreter start
and pins a backend, which makes the JAX_PLATFORMS env var alone too late
— jax's config snapshots it on first import.  Entry points (examples,
launcher) call this before their first backend use to route the request
through jax.config instead.  When the env var is unset this is a no-op
and jax picks its default backend (on TPU hosts: the TPU).
"""

from __future__ import annotations

import os


def apply_platform_from_env() -> None:
    want = os.environ.get("JAX_PLATFORMS") or os.environ.get(
        "JAX_PLATFORM_NAME")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
