"""TSEngine push direction: scheduler-paired worker-to-worker merging.

Reimplements the reference's push-side overlay (ref: ProcessAskPushCommand
van.cc:1197-1252; worker-side merge WorkersMerge kvstore_dist.h:91-173;
TS_Process re-ask loop kv_app.h:1111-1179): instead of every worker
pushing its gradient to the server (N uplinks), ready workers ask the
scheduler for a pairing; the scheduler matches two, one ships its
gradients to the other, the receiver merges (tracking ``num_merge``
contributions) and re-asks.  When a single holder carries all
``num_workers`` contributions, the scheduler answers "server" and that
worker pushes the merged gradient set once — a merge tree shaped by
which links are free, halving server fan-in pressure.

Control plane: Control.ASK_PUSH → Control.REPLY with
``{"action": "send"|"recv"|"server", "peer": ...}``.  Data plane: one
``Cmd.TS_PUSH_MERGE`` data request carrying the concatenated gradient
set.  API: ``TsPushWorker.merge_push(grads) -> merged or None`` — the
elected worker receives the full merged set back and is responsible for
the single server push; everyone else gets None.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomx_tpu.core.config import NodeId
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.transport.message import Control, Domain, Message

TS_PUSH_MERGE_CMD = 100  # data-plane cmd for merge relays


class TsPushScheduler:
    """Pairs ready pushers per round (ref: van.cc:1197-1252)."""

    def __init__(self, postoffice: Postoffice, num_workers: int,
                 pending_ttl_s: Optional[float] = None):
        # NOTE: pending_ttl_s must stay BELOW the workers' ask timeout
        # (config.ts_ask_timeout_s) — an entry older than its asker's
        # timeout belongs to a worker that already gave up and must never be
        # paired against.  Defaults come from Config (VERDICT r1: these
        # were hard-coded).
        self.po = postoffice
        self.num_workers = num_workers
        cfg = postoffice.config
        self.pending_ttl_s = (pending_ttl_s if pending_ttl_s is not None
                              else cfg.ts_push_pair_ttl_s)
        if self.pending_ttl_s >= cfg.ts_ask_timeout_s:
            raise ValueError(
                f"ts_push_pair_ttl_s ({self.pending_ttl_s}) must be below "
                f"ts_ask_timeout_s ({cfg.ts_ask_timeout_s}): a pairing "
                "that outlives the asker's patience pairs dead waiters")
        self._mu = threading.Lock()
        # iter -> list of (asker Message, num_merge, enqueue_time)
        self._pending: Dict[int, List[Tuple[Message, int, float]]] = {}
        self._member_seq = -1
        postoffice.add_control_hook(self._on_membership)
        postoffice.add_control_hook(self._on_control)

    def _on_membership(self, msg: Message) -> bool:
        """Track the party's live worker count (seq-stamped broadcast
        from the server): ``num_merge >= num_workers`` is the "holder
        has everything, go to the server" decision, so a stale count
        under dynamic membership either elects too early (a joiner's
        contribution rides the NEXT round) or never (leaver counted
        forever -> every holder waits out the pairing TTL)."""
        body = msg.body if isinstance(msg.body, dict) else {}
        if (msg.control is not Control.ADD_NODE or msg.request
                or body.get("event") != "membership"):
            return False
        seq = body.get("seq")
        with self._mu:
            if seq is not None and seq <= self._member_seq:
                return False  # stale; let other hooks see it too
            if seq is not None:
                self._member_seq = seq
            self.num_workers = int(body["num_workers"])
        return False  # not exclusive: the pull scheduler consumes it too

    def _on_control(self, msg: Message) -> bool:
        import time as _time

        if msg.control is not Control.ASK_PUSH:
            return False
        body = msg.body or {}
        it = body.get("iter", 0)  # any hashable round token (int or str)
        nm = int(body.get("num_merge", 1))
        # pairing bucket: STRING tokens (the inter-party servers' per-key
        # "key:round" form) pair exactly; INTEGER tokens are per-worker
        # call counters, which drift across dynamic membership (a joiner
        # starts at 1 while statics are at round r) — but worker-tier
        # participants are always in the same BSP round (no worker can
        # advance before the round completes), so one shared bucket is
        # safe and keeps a joiner pair-able instead of timing out every
        # round's TTL
        bucket = it if isinstance(it, str) else "__worker_round__"
        replies = []
        now = _time.monotonic()
        with self._mu:
            # expire abandoned entries (their worker timed out waiting for
            # a pairing that can no longer happen) so the dict can't leak
            # and dead waiters are never paired against
            for k in list(self._pending):
                self._pending[k] = [e for e in self._pending[k]
                                    if now - e[2] < self.pending_ttl_s]
                if not self._pending[k]:
                    del self._pending[k]
            pend = self._pending.setdefault(bucket, [])
            if not isinstance(it, str):
                # one sender, two outstanding DEFAULT-token asks: a second
                # concurrent merge_push() without an explicit per-key
                # token.  Pairing it would silently cross-merge two
                # different rounds' gradients into one accumulator (the
                # shared __worker_round__ bucket assumes lockstep BSP —
                # one ask per worker at a time); refuse loudly instead
                # and let the caller's merge_push raise (advisor r5).
                dup = next((e for e in pend
                            if str(e[0].sender) == str(msg.sender)), None)
                if dup is not None:
                    replies.append((msg, {
                        "action": "error", "iter": it,
                        "error": f"{msg.sender} has a concurrent "
                                 "default-token merge_push outstanding; "
                                 "concurrent per-key merges must pass an "
                                 "explicit string round token"}))
            if replies:
                pass  # rejected above — leave the pending entry untouched
            elif nm >= self.num_workers:
                # this node holds everything → send to server
                replies.append((msg, {"action": "server", "iter": it}))
                self._pending.pop(bucket, None)
            elif pend:
                other, other_nm, _t, other_it = pend.pop(0)
                # the longer-waiting node receives; the newcomer sends.
                # Each reply echoes ITS asker's own token — that is what
                # the asker's waiter is keyed on (cross-token pairing
                # would otherwise strand the older asker)
                replies.append((other, {"action": "recv",
                                        "peer": str(msg.sender),
                                        "num_merge": other_nm + nm,
                                        "iter": other_it}))
                replies.append((msg, {"action": "send",
                                      "peer": str(other.sender),
                                      "peer_iter": other_it, "iter": it}))
            else:
                pend.append((msg, nm, now, it))
        for req, body_out in replies:
            self.po.van.send(req.reply_to(control=Control.REPLY,
                                          body=body_out))
        return True


class TsPushWorker:
    """Worker-side merge participant.

    Usage per round: ``merged = tsp.merge_push({tid: grad_array, ...})``;
    if ``merged`` is not None this worker was elected to push the full
    merged set to the server (divide by num_workers upstream as usual).
    """

    def __init__(self, postoffice: Postoffice, scheduler: NodeId,
                 kv_worker, domain: Domain = Domain.LOCAL):
        self.po = postoffice
        self.scheduler = scheduler
        self.domain = domain
        self._cv = threading.Condition()
        # per-round-token state so several merges (one per key) can run
        # concurrently on this node without stealing each other's
        # replies/relays
        self._replies: Dict[object, dict] = {}
        self._incoming: List[Tuple[dict, dict]] = []  # (grads, body)
        self._iter = 0
        postoffice.add_control_hook(self._on_control)
        # chain with any existing handler (the pull-direction overlay also
        # routes inbound data requests through ts_handler)
        prev = kv_worker.ts_handler

        def dispatch(msg: Message):
            if msg.cmd == TS_PUSH_MERGE_CMD:
                self._on_merge_msg(msg)
            elif prev is not None:
                prev(msg)
            else:
                raise AssertionError(f"unexpected TS request: {msg}")

        kv_worker.ts_handler = dispatch

    # ---- control ------------------------------------------------------------
    _STALE_S = 120.0  # tokens are never re-asked; entries older than any
    #                   possible waiter are garbage from aborted rounds

    def _prune_locked(self):
        import time as _time

        now = _time.monotonic()
        for k in [k for k, (_, t) in self._replies.items()
                  if now - t > self._STALE_S]:
            del self._replies[k]
        self._incoming = [e for e in self._incoming
                          if now - e[2] <= self._STALE_S]

    def _on_control(self, msg: Message) -> bool:
        import time as _time

        if msg.control is Control.REPLY and isinstance(msg.body, dict) \
                and "action" in msg.body:
            with self._cv:
                self._prune_locked()
                self._replies[msg.body.get("iter")] = (msg.body,
                                                       _time.monotonic())
                self._cv.notify_all()
            return True
        return False

    def _ask(self, it, num_merge: int,
             timeout: Optional[float] = None) -> dict:
        timeout = (timeout if timeout is not None
                   else self.po.config.ts_ask_timeout_s)
        with self._cv:
            self._replies.pop(it, None)
        self.po.van.send(Message(
            recipient=self.scheduler, control=Control.ASK_PUSH,
            domain=self.domain, body={"iter": it, "num_merge": num_merge}))
        with self._cv:
            ok = self._cv.wait_for(lambda: it in self._replies,
                                   timeout=timeout)
            if not ok:
                raise TimeoutError(f"{self.po.node}: ASK_PUSH timed out")
            return self._replies.pop(it)[0]

    # ---- data plane ---------------------------------------------------------
    def _on_merge_msg(self, msg: Message):
        import time as _time

        grads = {}
        off = 0
        for tid, ln in zip(msg.keys, msg.lens):
            grads[int(tid)] = np.array(msg.vals[off:off + ln], copy=True)
            off += ln
        with self._cv:
            self._prune_locked()
            self._incoming.append((grads, msg.body or {}, _time.monotonic()))
            self._cv.notify_all()

    def _send_grads(self, peer: NodeId, grads: dict, num_merge: int, it):
        tids = sorted(grads)
        keys = np.array(tids, dtype=np.int64)
        vals = np.concatenate([grads[t].ravel() for t in tids])
        lens = np.array([grads[t].size for t in tids], dtype=np.int64)
        self.po.van.send(Message(
            recipient=peer, domain=self.domain, app_id=0, customer_id=0,
            timestamp=-1, request=True, push=True, cmd=TS_PUSH_MERGE_CMD,
            keys=keys, vals=vals.astype(np.float32), lens=lens,
            body={"iter": it, "num_merge": num_merge},
        ))

    def _wait_incoming(self, it,
                       timeout: Optional[float] = None) -> Tuple[dict, dict]:
        timeout = (timeout if timeout is not None
                   else self.po.config.ts_ask_timeout_s)
        def find():
            for i, (_, body, _t) in enumerate(self._incoming):
                if body.get("iter") == it:
                    return i
            return None

        with self._cv:
            ok = self._cv.wait_for(lambda: find() is not None,
                                   timeout=timeout)
            if not ok:
                raise TimeoutError(f"{self.po.node}: merge relay for round "
                                   f"{it!r} never arrived")
            grads, body, _ = self._incoming.pop(find())
            return grads, body

    # ---- public -------------------------------------------------------------
    def merge_push(self, grads: Dict[int, np.ndarray],
                   it=None) -> Optional[Tuple[dict, int]]:
        """Join this round's merge tree.  Returns ``(merged_grads,
        num_merge)`` if this worker must push to the server, else None
        (our contribution rides with a peer).

        ``it`` is the round token participants pair on; default is a
        per-worker call counter (correct when all participants call in
        lockstep, the worker-loop case).  Callers whose rounds complete
        in differing batch orders (the inter-party server case) must pass
        an explicit per-key token instead.

        Degradation: if the scheduler or an expected peer goes silent
        (TimeoutError), the holder pushes what it has with its partial
        ``num_merge`` — the server accumulates counts across pushes, so
        two partial pushes still complete the round exactly; only a
        contribution in flight to a dead node is lost (and then the
        request-replay layer is the recovery path)."""
        if it is None:
            self._iter += 1
            it = self._iter
        grads = {t: np.asarray(g, np.float32).ravel() for t, g in grads.items()}
        num_merge = 1
        while True:
            try:
                reply = self._ask(it, num_merge)
            except TimeoutError:
                return grads, num_merge  # scheduler gone: push direct
            action = reply["action"]
            if action == "error":
                # scheduler refused the ask (e.g. a concurrent
                # default-token merge from this node) — a programming
                # error, not a degradation: surface it, never
                # cross-merge rounds silently
                raise RuntimeError(f"ASK_PUSH rejected: {reply['error']}")
            if action == "server":
                return grads, num_merge
            if action == "send":
                # label the relay with the RECEIVER's round token (the
                # scheduler echoes it as peer_iter): the receiver's
                # waiter is keyed on its own counter, which can differ
                # from ours under dynamic membership
                self._send_grads(NodeId.parse(reply["peer"]), grads,
                                 num_merge, reply.get("peer_iter", it))
                return None
            # recv: wait for the peer's set, merge (ref: WorkersMerge —
            # elementwise sum of contributions), carry the summed count
            try:
                peer_grads, body = self._wait_incoming(it)
            except TimeoutError:
                return grads, num_merge  # peer gone: push what we hold
            for t, g in peer_grads.items():
                grads[t] = grads.get(t, 0) + g
            num_merge += int(body.get("num_merge", 1))
