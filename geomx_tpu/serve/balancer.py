"""Liveness-aware client-side load balancer for the serve tier.

PR 8 left the read path pinned to ONE hand-picked replica
(``ReplicaClient.retarget`` was the whole failover story): a SIGKILLed
or stale-stuck replica cost every subsequent read a full timeout.
:class:`ServeBalancer` is the missing client half of the serving plane
(docs/serving.md "Serving plane"):

- **power-of-two-choices** over the replica set: each read samples two
  candidate replicas and takes the one with the lower load score
  (locally-observed outstanding reads, then the cluster-state QPS) —
  the classic p2c result: near-best-of-N balance at O(1) cost;
- **cluster-state view**: the candidate set is fed by the global
  scheduler's ``Ctrl.CLUSTER_STATE`` replica table (freshness /
  staleness / qps / retired), cached and refreshed at most every
  ``Config.serve_lb_refresh_s`` — a replica the telemetry plane
  already knows is dead, stale past the bound, or retired is skipped
  WITHOUT burning a probe on it;
- **per-replica health accounting**: consecutive errors / timeouts /
  staleness rejects eject a replica from the candidate set
  (``serve_eject_errors``); after ``serve_probe_s`` it gets exactly
  one HALF-OPEN trial read — success restores it, failure re-opens the
  breaker.  A dead replica costs one failed read, not a stream of them;
- **shed honoring**: an admission-control ``RETRY_AFTER`` error
  (``ReplicaError.shed``) deprioritizes the replica for the suggested
  backoff (jittered) and the read retries ELSEWHERE immediately —
  the explicit-load-shedding contract, client side;
- **bounded attempt latency**: every attempt runs under
  ``serve_attempt_timeout_s``, so the FIRST failure on a dead target
  triggers an immediate re-pick instead of burning the caller's whole
  deadline (the PR 8 regression this module fixes).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomx_tpu.core.config import Config, Role
from geomx_tpu.kvstore.common import Ctrl
from geomx_tpu.ps import Postoffice
from geomx_tpu.serve.client import ReplicaClient, ReplicaError
from geomx_tpu.transport.message import Domain
from geomx_tpu.utils.metrics import system_counter


class _Health:
    """Per-replica circuit state: closed (serving) -> open (ejected)
    -> half-open (one probe in flight) -> closed/open."""

    __slots__ = ("consec_errors", "open_until", "probing",
                 "depri_until", "outstanding", "picks", "errors",
                 "sheds")

    def __init__(self):
        self.consec_errors = 0
        self.open_until = 0.0   # 0 = closed
        self.probing = False    # a half-open trial is in flight
        self.depri_until = 0.0  # shed backoff window
        self.outstanding = 0    # reads in flight through THIS balancer
        self.picks = 0
        self.errors = 0
        self.sheds = 0

    def open_now(self, now: float) -> bool:
        return self.open_until > now


class ServeBalancer:
    """One per read frontend; owns one :class:`ReplicaClient` per
    replica rank on the caller's postoffice."""

    def __init__(self, postoffice: Postoffice,
                 config: Optional[Config] = None,
                 replicas: Optional[Sequence[int]] = None,
                 advertise: Optional[tuple] = None,
                 seed: Optional[int] = None):
        self.po = postoffice
        self.config = config or postoffice.config
        topo = postoffice.topology
        ranks = (list(replicas) if replicas is not None
                 else list(range(topo.num_replicas)))
        assert ranks, "ServeBalancer needs at least one replica rank"
        self.clients: Dict[int, ReplicaClient] = {
            r: ReplicaClient(postoffice, self.config, replica=r,
                             customer_id=3 + i, advertise=advertise)
            for i, r in enumerate(ranks)}
        self.ranks = ranks
        cfg = self.config
        self.bound_s = float(cfg.serve_staleness_s)
        self.attempt_timeout_s = float(cfg.serve_attempt_timeout_s)
        self.eject_errors = int(cfg.serve_eject_errors)
        self.probe_s = float(cfg.serve_probe_s)
        self.view_refresh_s = float(cfg.serve_lb_refresh_s)
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self._health: Dict[int, _Health] = {r: _Health() for r in ranks}
        # cached cluster-state replica table ({rank: entry}) + the next
        # instant a refresh may be attempted (throttle, also after
        # failures — a dark scheduler must not stall every pick)
        self._view: Dict[int, dict] = {}
        self._view_next = 0.0
        self._gsched = topo.global_scheduler()
        n = str(postoffice.node)
        self.lb_picks = system_counter(f"{n}.lb_picks")
        self.lb_failovers = system_counter(f"{n}.lb_failovers")
        self.lb_sheds = system_counter(f"{n}.lb_sheds")
        self.lb_ejections = system_counter(f"{n}.lb_ejections")
        self.lb_probes = system_counter(f"{n}.lb_probes")
        self.lb_recoveries = system_counter(f"{n}.lb_recoveries")

    # ---- cluster-state view --------------------------------------------------
    def refresh_view(self, timeout: float = 2.0) -> bool:
        """One ``Ctrl.CLUSTER_STATE`` round trip; False when the
        scheduler is unreachable (the cached view — possibly empty —
        keeps serving picks)."""
        client = self.clients[self.ranks[0]]
        try:
            ts = client.send_cmd(self._gsched, Ctrl.CLUSTER_STATE,
                                 body=client._body(),
                                 domain=Domain.GLOBAL, wait=False)
            client.customer.wait(ts, timeout=timeout)
            reply = client.cmd_response(ts)
        except (TimeoutError, KeyError, OSError):
            return False
        if not isinstance(reply, dict):
            return False
        table = reply.get("replicas") or {}
        view: Dict[int, dict] = {}
        for k, ent in table.items():
            try:
                view[int(k)] = dict(ent)
            except (TypeError, ValueError):
                continue
        with self._mu:
            self._view = view
        return True

    def _maybe_refresh_view(self, now: float):
        with self._mu:
            if now < self._view_next:
                return
            # claim the slot before the (blocking) query so concurrent
            # readers don't stampede the scheduler
            self._view_next = now + max(self.view_refresh_s, 0.1)
        self.refresh_view(timeout=min(1.0, self.attempt_timeout_s))

    def _view_ok(self, rank: int) -> bool:
        """False only when the cached cluster-state view POSITIVELY
        disqualifies the replica (dead / retired / stale past the
        bound) — an absent or silent view never blocks serving."""
        ent = self._view.get(rank)
        if not ent:
            return True
        if ent.get("alive") is False:
            return False
        if ent.get("retired"):
            return False
        s = ent.get("staleness_s")
        if isinstance(s, (int, float)) and s > self.bound_s:
            return False
        return True

    # ---- candidate choice ----------------------------------------------------
    def candidates(self, now: Optional[float] = None,
                   exclude: Sequence[int] = ()) -> List[int]:
        """Ranks currently eligible for a pick: breaker closed (or due
        a half-open probe), not disqualified by the cluster-state view,
        not inside a shed backoff — with each filter relaxed in that
        order rather than returning an empty set (a degraded tier still
        wants its best shot routed somewhere)."""
        now = time.monotonic() if now is None else now
        ex = set(exclude)
        with self._mu:
            base = []
            for r in self.ranks:
                if r in ex:
                    continue
                h = self._health[r]
                if h.open_until and h.open_now(now):
                    continue  # ejected, probe not due yet
                if h.open_until and h.probing:
                    continue  # half-open: one trial at a time
                base.append(r)
            healthy = [r for r in base if self._view_ok(r)]
            if healthy:
                base = healthy
            fresh = [r for r in base
                     if self._health[r].depri_until <= now]
            return fresh or base

    def pick(self, exclude: Sequence[int] = ()) -> Optional[int]:
        """Power-of-two-choices: sample two eligible replicas, keep the
        lower (outstanding, qps) score.  Returns None when nothing is
        eligible."""
        now = time.monotonic()
        self._maybe_refresh_view(now)
        cands = self.candidates(now, exclude)
        if not cands:
            return None
        if len(cands) == 1:
            choice = cands[0]
        else:
            a, b = self._rng.sample(cands, 2)
            choice = min((a, b), key=self._score)
        with self._mu:
            h = self._health[choice]
            h.picks += 1
            h.outstanding += 1
            if h.open_until:  # due for its half-open trial
                h.probing = True
                self.lb_probes.inc()
        self.lb_picks.inc()
        return choice

    def _score(self, rank: int) -> Tuple[float, float]:
        h = self._health[rank]
        ent = self._view.get(rank) or {}
        qps = ent.get("serve_qps")
        return (float(h.outstanding),
                float(qps) if isinstance(qps, (int, float)) else 0.0)

    # ---- outcome accounting --------------------------------------------------
    def _on_success(self, rank: int):
        with self._mu:
            h = self._health[rank]
            h.outstanding = max(0, h.outstanding - 1)
            recovered = h.open_until > 0
            h.consec_errors = 0
            h.open_until = 0.0
            h.probing = False
            h.depri_until = 0.0
        if recovered:
            self.lb_recoveries.inc()
            print(f"{self.po.node}: replica:{rank} recovered "
                  "(half-open probe succeeded) — restored to the "
                  "candidate set", flush=True)

    def _on_shed(self, rank: int, retry_after_s: float):
        now = time.monotonic()
        backoff = max(retry_after_s, 1e-3)
        backoff *= 1.0 + self._rng.uniform(0.0, 0.5)  # jitter: a
        #                 synchronized client fleet must not re-dogpile
        #                 the shedding replica at one instant
        with self._mu:
            h = self._health[rank]
            h.outstanding = max(0, h.outstanding - 1)
            h.sheds += 1
            # a shed is a RESPONSE: the replica is alive, just loaded —
            # close the breaker (a probe answered with a shed counts as
            # recovery) but back off for the suggested window
            h.consec_errors = 0
            h.open_until = 0.0
            h.probing = False
            h.depri_until = max(h.depri_until, now + backoff)
        self.lb_sheds.inc()

    def _on_error(self, rank: int):
        now = time.monotonic()
        with self._mu:
            h = self._health[rank]
            h.outstanding = max(0, h.outstanding - 1)
            h.errors += 1
            h.consec_errors += 1
            was_probe = h.probing
            h.probing = False
            eject = (h.consec_errors >= self.eject_errors or was_probe
                     or h.open_until > 0)
            if eject:
                first = h.open_until == 0.0
                h.open_until = now + self.probe_s
            else:
                first = False
        if eject and first:
            self.lb_ejections.inc()
            print(f"{self.po.node}: replica:{rank} ejected after "
                  f"{self.eject_errors} consecutive failures — "
                  f"half-open probe in {self.probe_s:.1f}s", flush=True)

    # ---- read API ------------------------------------------------------------
    def _call(self, fn_name: str, args: tuple, kwargs: dict,
              timeout: Optional[float]) -> tuple:
        """One balanced read: pick -> bounded attempt -> on failure
        re-pick IMMEDIATELY (never burn the caller's whole deadline on
        one dead target).  Returns ``(result, rank)``."""
        deadline = time.monotonic() + (10.0 if timeout is None
                                       else float(timeout))
        tried: set = set()
        last_err: Optional[Exception] = None
        while True:
            now = time.monotonic()
            if now >= deadline:
                break
            rank = self.pick(exclude=tried)
            if rank is None:
                if not tried:
                    break  # nothing eligible at all
                # every replica was tried this pass: brief jittered
                # backoff, then a fresh pass (sheds/ejections may have
                # expired by then)
                tried.clear()
                pause = min(0.05 * (1 + self._rng.random()),
                            max(deadline - now, 0.0))
                if pause <= 0:
                    break
                time.sleep(pause)
                continue
            attempt = min(self.attempt_timeout_s,
                          max(deadline - now, 1e-3))
            client = self.clients[rank]
            try:
                res = getattr(client, fn_name)(*args, timeout=attempt,
                                               **kwargs)
            except ReplicaError as e:
                last_err = e
                tried.add(rank)
                if e.shed:
                    self._on_shed(rank, e.retry_after_s)
                else:
                    self._on_error(rank)
                self.lb_failovers.inc()
                continue
            except (TimeoutError, KeyError, OSError) as e:
                last_err = e
                tried.add(rank)
                self._on_error(rank)
                self.lb_failovers.inc()
                continue
            self._on_success(rank)
            return res, rank
        if last_err is not None:
            raise last_err
        raise TimeoutError(
            f"{self.po.node}: no serve replica eligible within the "
            "deadline (all ejected/deprioritized)")

    def pull(self, keys, timeout: Optional[float] = None):
        """Balanced SERVE_PULL; returns ``(KVPairs, meta)`` like
        :meth:`ReplicaClient.pull` (meta gains ``replica``)."""
        (kvs, meta), rank = self._call("pull", (keys,), {}, timeout)
        meta["replica"] = rank
        return kvs, meta

    def pull_tensor(self, tid: int, size: int,
                    timeout: Optional[float] = None):
        (arr, meta), rank = self._call("pull_tensor", (tid, size), {},
                                       timeout)
        meta["replica"] = rank
        return arr, meta

    def predict(self, x: np.ndarray, layers: List[tuple],
                relu: bool = True, timeout: Optional[float] = None):
        (out, meta), rank = self._call("predict", (x, layers),
                                       {"relu": relu}, timeout)
        meta["replica"] = rank
        return out, meta

    def list_keys(self, timeout: Optional[float] = None) -> List[int]:
        """Key discovery through any eligible replica."""
        keys, _rank = self._call("list_keys", (), {}, timeout)
        return keys

    # ---- observability -------------------------------------------------------
    def stats(self) -> dict:
        now = time.monotonic()
        with self._mu:
            per = {}
            for r in self.ranks:
                h = self._health[r]
                per[r] = {
                    "picks": h.picks, "errors": h.errors,
                    "sheds": h.sheds,
                    "consec_errors": h.consec_errors,
                    "ejected": h.open_now(now),
                    "probing": h.probing,
                    "deprioritized": h.depri_until > now,
                    "outstanding": h.outstanding,
                }
        return {
            "replicas": per,
            "picks": self.lb_picks.value,
            "failovers": self.lb_failovers.value,
            "sheds": self.lb_sheds.value,
            "ejections": self.lb_ejections.value,
            "probes": self.lb_probes.value,
            "recoveries": self.lb_recoveries.value,
        }

    def stop(self):
        for c in self.clients.values():
            c.stop()
