#!/usr/bin/env bash
# Acceptance config: hfa_sync (mirrors the reference scripts/cpu/run_hfa_sync.sh)
exec "$(dirname "$0")/run_cluster.sh" --hfa
