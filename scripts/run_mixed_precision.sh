#!/usr/bin/env bash
# Acceptance config: mixed_precision (mirrors the reference scripts/cpu/run_mixed_precision.sh)
exec "$(dirname "$0")/run_cluster.sh" --compression mpq
