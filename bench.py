#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline", ...extras}.

Round-1 failure mode (BENCH_r01.json rc=1, parsed null): the axon TPU
tunnel flaked during backend init and one exception killed the run.
This harness therefore:

- runs every device benchmark in a **subprocess** with a hard timeout
  and retry/backoff, so a hung backend init (observed: jax.devices()
  blocking >2 min) can never wedge the whole bench;
- always runs the CPU-only WAN codec benchmark, so even a dead tunnel
  still yields a real number (the reference's headline is WAN-traffic
  reduction, README.md:21-45);
- on TPU failure emits the WAN figure as the primary metric plus an
  "error" field — never rc!=0, never an empty line.

Benchmarks:
- **cnn**   CIFAR-10-shape CNN images/sec/chip (BASELINE.md metric #1).
  The step loop runs on-device via lax.scan — one dispatch per
  measurement — because the axon tunnel adds O(100ms) per Python
  dispatch, which would measure the tunnel, not the chip.
- **mfu**   flagship transformer (models/transformer.py) fwd+bwd+adam,
  bf16: achieved TFLOP/s vs the chip's peak (VERDICT r1 item 1).
- **quant** on-chip pallas 2-bit quantization throughput vs the host
  C++/numpy codec (VERDICT r1 item 2).
- **wan**   WAN bytes/step per codec config on the full two-tier stack
  (CPU, in-proc sim).

vs_baseline: BASELINE.md's north star is >=0.9x the per-chip throughput
of an A100 running the reference CUDA build on the same CNN.  No A100
is reachable (zero egress), so the A100 reference is **derived**, not
measured: images/sec = EFF_A100 * A100_PEAK_BF16 / CNN_FLOPS_PER_IMAGE,
with the assumed efficiency stated in the output.  For the tiny
2-conv/3-dense CNN the honest statement is that both chips are
launch/input-bound; the FLOP-derived bound with a generous efficiency
is an upper estimate of the reference, making vs_baseline conservative.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent
sys.path.insert(0, str(ROOT))

BATCH = 1024
STEPS = 32          # per on-device scan segment
A100_PEAK_BF16 = 312e12
EFF_A100 = 0.20     # assumed FLOP efficiency of the CUDA reference on this
#                     small CNN (generous: small convs at batch 1024 rarely
#                     exceed ~20% on A100; stated in output for audit)
V5E_PEAK_BF16 = 197e12  # TPU v5e (device reports "TPU v5 lite")


# --------------------------------------------------------------------------
# children (each runs in its own subprocess; prints one JSON line)
# --------------------------------------------------------------------------

def _cnn_flops_per_image():
    """Analytic fwd FLOPs/image of models/cnn.py's CNN at 32x32x3; the
    train step is ~3x fwd (fwd + 2x in bwd)."""
    f = 0.0
    # conv1: 32x32x3 -> 32x32x32, 3x3;  conv2: pool-> 16x16x64, 3x3
    f += 2 * 32 * 32 * 32 * (3 * 3 * 3)
    f += 2 * 16 * 16 * 64 * (3 * 3 * 32)
    # dense: flatten 8*8*64=4096 -> 128 -> 64 -> 10 (models/cnn.py)
    f += 2 * (8 * 8 * 64) * 128 + 2 * 128 * 64 + 2 * 64 * 10
    return 3.0 * f


def child_cnn():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from geomx_tpu.models import create_cnn_state

    rng = jax.random.PRNGKey(0)
    model, params, _ = create_cnn_state(
        rng, input_shape=(BATCH, 32, 32, 3), num_classes=10)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    def loss_fn(p, x, y):
        logits = model.apply(p, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    def step(carry, _):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = tx.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), loss

    @jax.jit
    def run_steps(p, s):
        (p, s), losses = jax.lax.scan(step, (p, s), None, length=STEPS)
        return p, s, losses[-1]

    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (BATCH, 32, 32, 3), dtype=np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 10, BATCH, dtype=np.int32))

    # compile + warmup; scalar readback is the sync point (on the remote
    # tunnel block_until_ready can return before execution finishes)
    params, opt_state, loss = run_steps(params, opt_state)
    _ = float(loss)

    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params, opt_state, loss = run_steps(params, opt_state)
        _ = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)

    ips = BATCH * STEPS / best_dt
    a100_ref = EFF_A100 * A100_PEAK_BF16 / _cnn_flops_per_image()
    print(json.dumps({
        "images_per_sec": round(ips, 1),
        "vs_baseline": round(ips / (0.9 * a100_ref), 3),
        "a100_ref_derivation": {
            "a100_images_per_sec": round(a100_ref, 1),
            "method": "EFF_A100 * A100_PEAK_BF16 / CNN_FLOPS_PER_IMAGE",
            "eff_a100": EFF_A100,
            "cnn_train_flops_per_image": _cnn_flops_per_image(),
        },
        "timing": "best_of_3_min, 32-step on-device scan",
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
    }))


# flagship MFU config: MXU-friendly shapes, fits v5e 16 GB with adam
MFU_CFG = dict(vocab=8192, d_model=2048, n_heads=16, n_layers=8,
               d_ff=8192, max_seq=2048)
MFU_BATCH = 2
MFU_STEPS = 8


def _transformer_train_flops_per_step(cfg, batch, seq):
    """Standard 6*N*T + attention-matmul term (12*L*T*seq*d_model*3 for
    fwd+bwd), counting the train step (fwd + 2x bwd)."""
    n_params = (cfg["vocab"] * cfg["d_model"]          # embed (tied head)
                + cfg["max_seq"] * cfg["d_model"]      # pos
                + cfg["n_layers"] * 12 * cfg["d_model"] ** 2)
    tokens = batch * seq
    dense = 6.0 * n_params * tokens
    attn = 12.0 * cfg["n_layers"] * tokens * seq * cfg["d_model"]
    return dense + attn, n_params


def child_mfu():
    import jax
    import jax.numpy as jnp
    import optax

    from geomx_tpu.models.transformer import (
        TransformerConfig, init_params, lm_loss, make_apply)

    cfg = TransformerConfig(**MFU_CFG)
    params = init_params(cfg, jax.random.PRNGKey(0))
    apply_fn = make_apply(cfg)
    tx = optax.adam(1e-4)
    opt_state = tx.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (MFU_BATCH, MFU_CFG["max_seq"]), 0,
        MFU_CFG["vocab"], dtype=jnp.int32)

    def step(carry, _):
        p, s = carry
        loss, grads = jax.value_and_grad(
            lambda p_: lm_loss(apply_fn, p_, tokens))(p)
        updates, s = tx.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), loss

    @jax.jit
    def run_steps(p, s):
        (p, s), losses = jax.lax.scan(step, (p, s), None, length=MFU_STEPS)
        return p, s, losses[-1]

    params, opt_state, loss = run_steps(params, opt_state)
    _ = float(loss)
    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params, opt_state, loss = run_steps(params, opt_state)
        _ = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)

    flops_per_step, n_params = _transformer_train_flops_per_step(
        MFU_CFG, MFU_BATCH, MFU_CFG["max_seq"])
    achieved = flops_per_step * MFU_STEPS / best_dt
    platform = jax.devices()[0].platform
    peak = V5E_PEAK_BF16 if platform in ("tpu", "axon") else None
    print(json.dumps({
        "achieved_tflops": round(achieved / 1e12, 2),
        "peak_tflops": peak and peak / 1e12,
        "mfu": peak and round(achieved / peak, 4),
        "model": (f"transformer d{MFU_CFG['d_model']} L{MFU_CFG['n_layers']} "
                  f"ff{MFU_CFG['d_ff']} seq{MFU_CFG['max_seq']} "
                  f"batch{MFU_BATCH} bf16 ({n_params/1e6:.0f}M params)"),
        "tokens_per_sec": round(
            MFU_BATCH * MFU_CFG["max_seq"] * MFU_STEPS / best_dt, 1),
        "platform": platform,
    }))


QUANT_MB = 64


def child_quant():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from geomx_tpu.ops.quantize import dequantize_2bit_tpu, quantize_2bit_tpu

    n = QUANT_MB * (1 << 20) // 4
    g = jnp.asarray(np.random.default_rng(0).standard_normal(n).astype(np.float32))
    r = jnp.zeros_like(g)

    packed, newr = quantize_2bit_tpu(g, r)          # compile + correctness
    out = dequantize_2bit_tpu(packed, n)
    _ = float(out[0]); _ = float(newr[0])
    # spot-check round-trip semantics on-device
    gi = np.asarray(g[:4096]); oi = np.asarray(out[:4096])
    expect = np.where(gi > 0.5, 0.5, np.where(gi < -0.5, -0.5, 0.0))
    if not np.allclose(oi, expect):
        raise AssertionError("on-chip 2bit round-trip mismatch")

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        packed, r = quantize_2bit_tpu(g, r)
    _ = float(packed[0])
    dev_dt = (time.perf_counter() - t0) / reps

    # host codec throughput for comparison
    from geomx_tpu.compression.codecs import TwoBitCodec
    codec = TwoBitCodec(threshold=0.5)
    gh = np.asarray(g)
    codec.compress(0, gh)                            # residual warmup
    t0 = time.perf_counter()
    for _ in range(reps):
        codec.compress(0, gh)
    host_dt = (time.perf_counter() - t0) / reps

    print(json.dumps({
        "tpu_quant_mbps": round(QUANT_MB / dev_dt, 1),
        "host_quant_mbps": round(QUANT_MB / host_dt, 1),
        "payload_mb": QUANT_MB,
        "platform": jax.devices()[0].platform,
        "roundtrip": "ok",
    }))


def child_overlap():
    """P3 staged-overlap vs BSP step time under a serialized WAN uplink
    (in-proc sim; VERDICT r1 item 3).  Thin wrapper over the shared
    harness in geomx_tpu.overlap — the regression test runs the same
    code, so benchmark and test cannot drift apart."""
    from geomx_tpu.overlap import overlap_vs_bsp_benchmark

    res = overlap_vs_bsp_benchmark()
    res["bsp_s_per_step"] = round(res["bsp_s_per_step"], 4)
    res["overlap_s_per_step"] = round(res["overlap_s_per_step"], 4)
    res["speedup"] = round(res["speedup"], 3)
    print(json.dumps(res))


def child_stress():
    """Server merge throughput at scale (VERDICT r1 item 5): one party of
    4 workers pushing a 50M-element tensor (200 MB) through the two-tier
    stack; reports merged GB/s per local server and the native threaded
    axpy's raw rate."""
    import numpy as np

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation
    from geomx_tpu.native import bindings

    N = 50_000_000
    rounds = 2
    sim = Simulation(Config(topology=Topology(num_parties=1,
                                              workers_per_party=4)))
    try:
        ws = sim.all_workers()
        for w in ws:
            w.init(0, np.zeros(N, np.float32))
        ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
        g = np.ones(N, np.float32)
        t0 = time.perf_counter()
        for _ in range(rounds):
            for w in ws:
                w.push(0, g)
            ws[0].pull_sync(0)
            for w in ws:
                w.wait_all()
        dt = time.perf_counter() - t0

        # native threaded axpy microbenchmark (the merge hot loop)
        acc = np.zeros(N, np.float32)
        t1 = time.perf_counter()
        bindings.accumulate(acc, g)
        axpy_dt = time.perf_counter() - t1
        print(json.dumps({
            "tensor_elems": N,
            "rounds": rounds,
            "round_s": round(dt / rounds, 3),
            "server_merged_gb_per_s": round(
                len(ws) * (N * 4 / 1e9) * rounds / dt, 3),
            "native_axpy_gb_per_s": round((N * 4 / 1e9) / axpy_dt, 2),
            "native_available": bindings.available(),
        }))
    finally:
        sim.shutdown()


def child_wan():
    """WAN bytes/step per codec config (in-proc sim, 2 parties x 1 worker —
    topology doesn't change the per-party WAN payload, codecs do)."""
    import numpy as np

    from geomx_tpu.core.config import Config, Topology
    from geomx_tpu.kvstore import Simulation

    N_BIG, N_SMALL = 400_000, 50_000
    STEPS_W = 4
    configs = {
        "vanilla": None,
        "fp16": {"type": "fp16"},
        "2bit": {"type": "2bit", "threshold": 0.5},
        "bsc": {"type": "bsc", "ratio": 0.01},
        "mpq": {"type": "mpq", "ratio": 0.01, "size_bound": 200_000},
    }
    out = {}
    for name, comp in configs.items():
        sim = Simulation(Config(
            topology=Topology(num_parties=2, workers_per_party=1)))
        try:
            ws = sim.all_workers()
            rng = np.random.default_rng(0)
            for w in ws:
                w.init(0, np.zeros(N_BIG, np.float32))
                w.init(1, np.zeros(N_SMALL, np.float32))
            ws[0].set_optimizer({"type": "sgd", "lr": 0.1})
            if comp is not None:
                for p in range(2):
                    sim.worker(p, 0).set_gradient_compression(comp)
            base = sim.wan_bytes()["wan_send_bytes"]
            for _ in range(STEPS_W):
                for tid, nel in ((0, N_BIG), (1, N_SMALL)):
                    g = rng.standard_normal(nel).astype(np.float32)
                    for w in ws:
                        w.push(tid, g)
                for w in ws:
                    w.pull_sync(0)
                    w.pull_sync(1)
            out[name] = (sim.wan_bytes()["wan_send_bytes"] - base) / STEPS_W
        finally:
            sim.shutdown()
    print(json.dumps({
        "bytes_per_step": {k: round(v, 1) for k, v in out.items()},
        "reduction": {k: round(out["vanilla"] / v, 2)
                      for k, v in out.items() if v > 0},
    }))


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------

def _run_child(name: str, timeout: float, env_extra=None):
    env = dict(os.environ)
    env.pop("BENCH_CHILD", None)
    if env_extra:
        env.update(env_extra)
    try:
        p = subprocess.run(
            [sys.executable, str(ROOT / "bench.py"), "--child", name],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout:.0f}s"
    if p.returncode != 0:
        tail = (p.stderr or p.stdout or "").strip().splitlines()[-6:]
        return None, f"rc={p.returncode}: " + " | ".join(tail)
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no JSON in child output"


def _run_tpu_child(name: str, timeout: float, attempts: int = 2,
                   backoff: float = 20.0):
    err = None
    for i in range(attempts):
        if i:
            time.sleep(backoff)
        res, err = _run_child(name, timeout)
        if res is not None:
            return res, None
    return None, err


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child",
                    choices=["cnn", "mfu", "quant", "wan", "overlap",
                             "stress"])
    ap.add_argument("--wan", action="store_true",
                    help="legacy: run only the WAN codec benchmark")
    ap.add_argument("--skip-tpu", action="store_true")
    args = ap.parse_args()

    if args.child:
        # route a CPU request through jax.config: the sandbox's
        # sitecustomize imports jax at interpreter start, so the env var
        # alone is too late and a dead TPU tunnel would hang the child
        from geomx_tpu.core.platform import apply_platform_from_env
        apply_platform_from_env()
        {"cnn": child_cnn, "mfu": child_mfu, "quant": child_quant,
         "wan": child_wan, "overlap": child_overlap,
         "stress": child_stress}[args.child]()
        return

    cpu_env = {"JAX_PLATFORMS": "cpu", "JAX_PLATFORM_NAME": "cpu"}
    wan, wan_err = _run_child("wan", timeout=300, env_extra=cpu_env)

    if args.wan:  # legacy single-benchmark mode: WAN codec numbers only
        print(json.dumps({
            "metric": "wan_bytes_per_step",
            "value": wan and wan["bytes_per_step"]["vanilla"],
            "unit": "bytes/step (vanilla; see configs)",
            "vs_baseline": None,
            "configs": wan and wan["bytes_per_step"],
            "reduction": wan and wan["reduction"],
            "error": wan_err,
        }))
        return

    overlap, overlap_err = _run_child("overlap", timeout=300,
                                      env_extra=cpu_env)
    stress, stress_err = _run_child("stress", timeout=600,
                                    env_extra=cpu_env)

    errors = {}
    cnn = mfu = quant = None
    if not args.skip_tpu:
        # the cnn child runs first and doubles as the tunnel probe:
        # jax.devices() has been observed to hang for minutes when the
        # tunnel is down, and the subprocess timeout contains that
        cnn, err = _run_tpu_child("cnn", timeout=420)
        if err:
            errors["cnn"] = err
        mfu, err = _run_tpu_child("mfu", timeout=600)
        if err:
            errors["mfu"] = err
        quant, err = _run_tpu_child("quant", timeout=420)
        if err:
            errors["quant"] = err
    if wan_err:
        errors["wan"] = wan_err
    if overlap_err:
        errors["overlap"] = overlap_err
    if stress_err:
        errors["stress"] = stress_err

    if cnn is not None:
        record = {
            "metric": "cifar10_cnn_images_per_sec_per_chip",
            "value": cnn["images_per_sec"],
            "unit": "images/sec/chip",
            "vs_baseline": cnn["vs_baseline"],
            "a100_ref_derivation": cnn["a100_ref_derivation"],
            "device": cnn.get("device"),
        }
    elif mfu is not None:
        record = {
            "metric": "transformer_achieved_tflops",
            "value": mfu["achieved_tflops"],
            "unit": "TFLOP/s",
            "vs_baseline": None,
        }
    else:
        record = {
            "metric": "wan_bytes_per_step",
            "value": wan and wan["bytes_per_step"]["vanilla"],
            "unit": "bytes/step (vanilla; see configs)",
            "vs_baseline": None,
            "error": "TPU benchmarks unavailable (see errors)",
        }
    if mfu:
        record["mfu"] = mfu
    if quant:
        record["quantize"] = quant
    if wan:
        record["wan"] = wan
    if overlap:
        record["overlap"] = overlap
    if stress:
        record["stress"] = stress
    if errors:
        record["errors"] = errors
    print(json.dumps(record))


if __name__ == "__main__":
    main()
