"""TCP fabric + multi-process launcher tests (the reference's
pseudo-distributed acceptance style, ref: tests/local.sh launching
role-tagged local processes)."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from geomx_tpu.core.config import Config, NodeId, Role, Topology
from geomx_tpu.transport import Domain, Message, Van
from geomx_tpu.transport.tcp import TcpFabric, default_address_plan


def free_base_port(span: int = 16):
    """Pick a base port with ``span`` consecutive free ports.

    Deliberately OUTSIDE the kernel ephemeral range (32768-60999 here):
    binding port 0 and closing returns an ephemeral port that an outgoing
    connection from any still-running test process can grab before our
    process binds it — and connect()-sockets don't set SO_REUSEADDR, so
    the fabric's EADDRINUSE retry loop can never win that race (observed:
    test_global_server_replacement_at_new_address flaking).
    """
    import random
    import socket

    for _ in range(200):
        base = random.randrange(18000, 28000)
        try:
            socks = []
            try:
                for i in range(span):
                    s = socket.socket()
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind(("127.0.0.1", base + i))
                    socks.append(s)
            finally:
                for s in socks:
                    s.close()
            return base
        except OSError:
            continue
    raise RuntimeError("no free port span found")


def test_tcp_fabric_roundtrip():
    topo = Topology(num_parties=1, workers_per_party=1)
    plan = default_address_plan(topo, base_port=free_base_port())
    fab = TcpFabric(plan)
    a, b = topo.workers(0)[0], topo.server(0)
    van_a, van_b = Van(a, fab), Van(b, fab)
    got = []
    ev = threading.Event()
    van_a.start(lambda m: None)
    van_b.start(lambda m: (got.append(m), ev.set()))
    van_a.send(Message(recipient=b, timestamp=3,
                       keys=np.array([1], np.int64),
                       vals=np.arange(5, dtype=np.float32),
                       lens=np.array([5], np.int64)))
    assert ev.wait(5)
    np.testing.assert_array_equal(got[0].vals, np.arange(5, dtype=np.float32))
    assert got[0].sender == a and got[0].timestamp == 3
    van_a.stop(); van_b.stop(); fab.shutdown()


@pytest.mark.slow
def test_launcher_full_topology_subprocess():
    """Stand up 1 party (scheduler+server+worker) + global tier as real
    OS processes over TCP; the worker trains and shuts the cluster down."""
    topo = Topology(num_parties=1, workers_per_party=1)
    base = free_base_port()
    env = dict(os.environ)
    env["JAX_PLATFORM_NAME"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    roles = [str(n) for n in topo.all_nodes()]
    procs = {}
    try:
        for r in roles:
            procs[r] = subprocess.Popen(
                [sys.executable, "-m", "geomx_tpu.launch", "--role", r,
                 "--parties", "1", "--workers", "1",
                 "--base-port", str(base), "--steps", "3"],
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        worker_out = outputs[str(topo.workers(0)[0])]
        assert "steps=3" in worker_out, worker_out
        for r, p in procs.items():
            assert p.returncode == 0, f"{r} rc={p.returncode}: {outputs[r][-800:]}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()


def _udp_stats(output: str):
    import re

    m = re.search(r"udp_tx=(\d+) udp_rx=(\d+) udp_dropped=(\d+)", output)
    return tuple(int(g) for g in m.groups()) if m else (0, 0, 0)


def test_udp_channel_roundtrip():
    """channel >= 1 messages travel as real UDP datagrams (DGT lossy
    channels, ref: zmq_van.h:95-193), reliable traffic stays on TCP."""
    topo = Topology(num_parties=1, workers_per_party=1)
    base = free_base_port()
    plan = default_address_plan(topo, base_port=base)
    a, b = topo.workers(0)[0], topo.server(0)
    # two fabrics = two "processes": the sender must NOT share a mailbox
    # with the receiver, or the local shortcut bypasses the sockets
    fab_a = TcpFabric({k: v for k, v in plan.items()})
    fab_b = TcpFabric({k: v for k, v in plan.items()})
    van_a, van_b = Van(a, fab_a), Van(b, fab_b)
    got = []
    ev = threading.Event()
    van_a.start(lambda m: None)
    van_b.start(lambda m: (got.append(m), ev.set()))
    van_a.send(Message(recipient=b, channel=2, seq=0, seq_end=5,
                       vals=np.arange(16, dtype=np.float32)))
    assert ev.wait(5)
    assert fab_a.udp_datagrams_sent == 1
    assert fab_b.udp_datagrams_recv == 1
    assert got[0].channel == 2
    np.testing.assert_array_equal(got[0].vals, np.arange(16, dtype=np.float32))
    van_a.stop(); van_b.stop()
    fab_a.shutdown(); fab_b.shutdown()


def test_udp_oversize_falls_back_to_tcp():
    """Payloads beyond the datagram limit ride the reliable conn (a
    misconfigured dgt_block_size must stay correct, just not lossy)."""
    topo = Topology(num_parties=1, workers_per_party=1)
    plan = default_address_plan(topo, base_port=free_base_port())
    a, b = topo.workers(0)[0], topo.server(0)
    fab_a, fab_b = TcpFabric(dict(plan)), TcpFabric(dict(plan))
    van_a, van_b = Van(a, fab_a), Van(b, fab_b)
    got = []
    ev = threading.Event()
    van_a.start(lambda m: None)
    van_b.start(lambda m: (got.append(m), ev.set()))
    big = np.zeros(100_000, dtype=np.float32)  # 400 KB > UDP_MAX
    van_a.send(Message(recipient=b, channel=1, vals=big))
    assert ev.wait(5)
    assert fab_a.udp_datagrams_sent == 0
    assert len(got[0].vals) == 100_000
    van_a.stop(); van_b.stop()
    fab_a.shutdown(); fab_b.shutdown()


@pytest.mark.slow
def test_dgt_mode1_over_real_sockets_with_loss():
    """The round-1 gap (VERDICT item 4): DGT mode 1 across real OS
    processes — lossy chunks as genuine UDP datagrams, 30% injected
    channel loss — and training still completes on every role."""
    topo = Topology(num_parties=1, workers_per_party=1)
    base = free_base_port()
    env = dict(os.environ)
    env["JAX_PLATFORM_NAME"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["GEOMX_CHANNEL_DROP_MSG"] = "30"  # % loss on lossy channels
    roles = [str(n) for n in topo.all_nodes()]
    procs = {}
    try:
        for r in roles:
            procs[r] = subprocess.Popen(
                [sys.executable, "-m", "geomx_tpu.launch", "--role", r,
                 "--parties", "1", "--workers", "1",
                 "--base-port", str(base), "--steps", "3", "--dgt", "1"],
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs.values()):
                break
            time.sleep(0.5)
        outputs = {}
        for r, p in procs.items():
            if p.poll() is None:
                p.kill()
            outputs[r] = p.communicate()[0]
        worker_out = outputs[str(topo.workers(0)[0])]
        assert "steps=3" in worker_out, worker_out
        for r, p in procs.items():
            assert p.returncode == 0, f"{r} rc={p.returncode}: {outputs[r][-800:]}"
        # the run is only meaningful if lossy chunks really rode UDP and
        # real loss occurred: the local server is the WAN pusher (DGT is
        # a GLOBAL-domain feature) and must have sent datagrams; with 30%
        # injected loss over 3 steps some must have been dropped
        tx, _, dropped = _udp_stats(outputs[str(topo.server(0))])
        assert tx > 0, f"no UDP datagrams sent: {outputs[str(topo.server(0))]}"
        assert dropped > 0, "no UDP loss occurred"
        _, rx, _ = _udp_stats(outputs[str(topo.global_servers()[0])])
        assert rx > 0, "global server received no datagrams"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
