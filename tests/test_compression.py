"""Codec numerics (unit) + compressed end-to-end flows (integration).

Mirrors the reference test strategy: codecs must round-trip within
quantization error, and compressed training must converge like vanilla
(ref: SURVEY.md §4; BSC numerics gradient_compression.cc:191-336)."""

import numpy as np
import pytest

from geomx_tpu.compression import (
    BroadcastCompressor, BscCodec, Fp16Codec, MpqSelector, TwoBitCodec,
    decompress_payload, make_push_codec,
)
from geomx_tpu.compression.codecs import pack_sparse, unpack_sparse
from geomx_tpu.core.config import Config, Topology
from geomx_tpu.kvstore import Simulation


# ---------- unit: pure codec numerics ----------------------------------------

def test_fp16_roundtrip():
    c = Fp16Codec()
    x = np.random.default_rng(0).standard_normal(1000).astype(np.float32)
    y = c.decompress(0, c.compress(0, x), 1000)
    np.testing.assert_allclose(y, x, atol=2e-3)
    assert c.compress(0, x).nbytes == x.nbytes // 2  # the 2x claim


def test_sparse_pack_unpack_preserves_large_indices():
    vals = np.array([1.5, -2.5], np.float32)
    idx = np.array([7, 2**30 + 3], np.int64)  # > 2^24: float32 would corrupt
    v2, i2 = unpack_sparse(pack_sparse(vals, idx))
    np.testing.assert_array_equal(i2, idx)
    np.testing.assert_array_equal(v2, vals)


def test_2bit_packing_ratio_and_residual_feedback():
    c = TwoBitCodec(threshold=0.5)
    rng = np.random.default_rng(1)
    x = rng.standard_normal(4096).astype(np.float32)
    payload = c.compress(0, x)
    assert payload.nbytes == 1024  # 16x vs float32
    # residual feedback: repeatedly sending the same gradient must
    # asymptotically transmit its full mass (ref: residual scheme
    # gradient_compression-inl.h:40-139)
    g = np.full(512, 0.2, np.float32)  # below threshold: only residual
    total = np.zeros_like(g)
    c2 = TwoBitCodec(threshold=0.5)
    n = 50
    for _ in range(n):
        total += c2.decompress(1, c2.compress(1, g), 512)
    # mass conservation: emitted = pushed - residual, residual < thr + step
    pushed = 0.2 * n
    assert pushed - 0.71 <= total.mean() <= pushed + 1e-5, total.mean()


def test_bsc_sends_top_entries_and_preserves_mass():
    c = BscCodec(ratio=0.05, momentum=0.0, sample_rate=0.5, seed=0)
    x = np.zeros(1000, np.float32)
    x[::100] = np.arange(1, 11, dtype=np.float32)  # 10 spikes
    payload = c.compress(0, x)
    dense = c.decompress(0, payload, 1000)
    # the largest spikes must be transmitted
    assert dense[900] == 10.0
    assert np.count_nonzero(dense) <= 120
    # unsent mass stays in the accumulator and eventually drains
    total = dense.copy()
    for _ in range(30):
        total += c.decompress(0, c.compress(0, np.zeros(1000, np.float32)), 1000)
    np.testing.assert_allclose(total, x, atol=1e-5)


def test_mpq_selector_splits_by_size():
    m = MpqSelector(size_bound=100)
    assert m.select(50) is m.fp16
    assert m.select(100) is m.bsc


def test_broadcast_compressor_view_tracking():
    """Subscriber's reconstructed view converges to the true weights."""
    bc = BroadcastCompressor(ratio=0.2)
    w0 = np.zeros(100, np.float32)
    bc.ensure_base(0, w0)
    true_w = w0.copy()
    sub_view = w0.copy()
    ver = 0
    rng = np.random.default_rng(3)
    for step in range(30):
        true_w = true_w + rng.standard_normal(100).astype(np.float32) * 0.1
        payload, tag, ver = bc.compress("sub", 0, true_w, echo_ver=ver)
        assert tag == "bsc"  # echo matches → always sparse
        sub_view = BroadcastCompressor.decompress_into(sub_view, payload)
    # after enough rounds the tracked view is close to the truth
    assert np.abs(sub_view - true_w).mean() < 0.2


def test_broadcast_compressor_version_handshake_resyncs():
    """The crash-safety handshake (stress-test FSA desync fix): any
    version mismatch — server restart (fresh compressor, subscriber
    echoes old ver), subscriber restart (echo 0 vs tracked>0), or a lost
    response (stale echo) — must force a dense "f32" resync; matched
    echoes stay sparse."""
    rng = np.random.default_rng(0)
    w = rng.standard_normal(50).astype(np.float32)

    bc = BroadcastCompressor(ratio=0.1)
    bc.ensure_base(0, np.zeros(50, np.float32))
    payload, tag, v1 = bc.compress("s", 0, w, echo_ver=0)
    assert tag == "bsc" and v1 == 1  # fresh pair: sparse from INIT base

    # server restarted: new compressor has no view, subscriber echoes v1
    bc2 = BroadcastCompressor(ratio=0.1)
    bc2.ensure_base(0, w)  # checkpointed weights
    payload, tag, v2 = bc2.compress("s", 0, w, echo_ver=v1)
    assert tag == "f32" and v2 > v1
    np.testing.assert_array_equal(payload, w)

    # matched echo after the resync: sparse again
    w2 = w + 0.5
    payload, tag, v3 = bc2.compress("s", 0, w2, echo_ver=v2)
    assert tag == "bsc" and v3 == v2 + 1
    # lost response: subscriber still echoes v2 → resync
    payload, tag, v4 = bc2.compress("s", 0, w2, echo_ver=v2)
    assert tag == "f32" and v4 > v3
    # subscriber replaced (echo 0 while tracked > 0) → resync
    payload, tag, _ = bc2.compress("s", 0, w2, echo_ver=0)
    assert tag == "f32"


def test_make_push_codec_rejects_unknown():
    with pytest.raises(ValueError):
        make_push_codec({"type": "zstd"})


# ---------- integration: compressed HiPS flows --------------------------------

def _set_compression(sim, cfg):
    """Rank-0 of each party configures its party server (ref semantics)."""
    for p in range(sim.topology.num_parties):
        sim.worker(p, 0).set_gradient_compression(cfg)


def _train(sim, steps=4, tensor_size=4000, lr=0.05):
    ws = sim.all_workers()
    for w in ws:
        w.init(0, np.zeros(tensor_size, np.float32))
    ws[0].set_optimizer({"type": "sgd", "lr": lr})
    rng = np.random.default_rng(0)
    for _ in range(steps):
        g = np.ones(tensor_size, np.float32) + 0.01 * rng.standard_normal(tensor_size).astype(np.float32)
        for w in ws:
            w.push(0, g)
        outs = [w.pull_sync(0) for w in ws]
    return outs


@pytest.mark.parametrize("ctype", ["fp16", "2bit", "bsc", "mpq"])
def test_compressed_training_moves_downhill(ctype):
    cfg = Config(topology=Topology(num_parties=2, workers_per_party=1))
    sim = Simulation(cfg)
    try:
        _set_compression(sim, {"type": ctype, "ratio": 0.05, "size_bound": 1000})
        outs = _train(sim, steps=6)
        for out in outs:
            assert out.mean() < -0.05, f"{ctype}: no descent ({out.mean()})"
        # all replicas agree
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
    finally:
        sim.shutdown()


def test_bsc_cuts_wan_bytes():
    def run(compression):
        cfg = Config(topology=Topology(num_parties=2, workers_per_party=1))
        sim = Simulation(cfg)
        try:
            if compression:
                _set_compression(sim, {"type": "bsc", "ratio": 0.01})
            _train(sim, steps=4, tensor_size=100_000)
            return sim.wan_bytes()["wan_send_bytes"]
        finally:
            sim.shutdown()

    plain = run(None)
    bsc = run("bsc")
    assert bsc < plain * 0.2, (plain, bsc)


def test_fp16_halves_wan_bytes():
    def run(compression):
        cfg = Config(topology=Topology(num_parties=2, workers_per_party=1))
        sim = Simulation(cfg)
        try:
            if compression:
                _set_compression(sim, {"type": "fp16"})
            _train(sim, steps=4, tensor_size=100_000)
            return sim.wan_bytes()["wan_send_bytes"]
        finally:
            sim.shutdown()

    plain = run(None)
    fp16 = run("fp16")
    assert fp16 < plain * 0.65, (plain, fp16)


def test_pull_compressor_resync_never_shares_peer_payload():
    """r5 regression (confirmed corruption): the pull compressor's
    same-round payload cache must key on (lineage, version), not
    version alone.  A lost response forces subscriber b onto a dense
    resync whose NEW version can numerically collide with a's
    sparse-path version; sharing a's cached delta would apply it
    against b's resynced base — permanently wrong replica (error stuck
    ~2.75 while a converges).  With the lineage fork, b resyncs once
    and both replicas keep tracking the weights."""
    from geomx_tpu.compression.codecs import BroadcastCompressor

    bc = BroadcastCompressor(ratio=0.05)
    n = 4096
    rng = np.random.default_rng(0)
    init = np.zeros(n, np.float32)
    bc.ensure_base(0, init)
    w = init.copy()
    replicas = {"a": init.copy(), "b": init.copy()}
    vers = {"a": 0, "b": 0}
    for r in range(40):
        w = w + rng.standard_normal(n).astype(np.float32) * 0.1
        wf = w.copy()
        wf.flags.writeable = False  # the store serves frozen aliases
        for s in ("a", "b"):
            payload, tag, ver = bc.compress(s, 0, wf, echo_ver=vers[s])
            if s == "b" and r == 3:
                continue  # b's response is LOST: replica + echo stay stale
            if tag == "f32":
                replicas[s] = np.array(payload, copy=True)
            else:
                replicas[s] = BroadcastCompressor.decompress_into(
                    replicas[s], payload)
            vers[s] = ver
    assert bc.resyncs == 1  # exactly the one heal for the lost response
    for s, rep in replicas.items():
        err = float(np.max(np.abs(rep - w)))
        assert err < 1.0, (s, err)  # broken cache: b stuck at ~2.75


def test_sampled_topk_native_numpy_parity():
    """advisor r5: hosts with and without the native library must
    produce IDENTICAL payloads.  (a) When the above-threshold count
    fits the cap, both paths select the same index set.  (b) The
    zero-entry edge (native scan finds nothing): the native path must
    mirror the numpy fallback's argmax floor — never 0 entries."""
    from geomx_tpu.compression import codecs

    # (a) real-parity: 5 clear spikes over tiny noise; the sampled
    # threshold lands between, so both backends select exactly the
    # spikes plus the same noise tail (count << cap → no tie-breaking
    # divergence between scan order and top-k order)
    rng = np.random.default_rng(3)
    delta = (rng.uniform(0, 1e-4, 10_000)).astype(np.float32)
    spikes = np.array([7, 170, 4242, 8888, 9999])
    delta[spikes] = 1.0
    if codecs._native() is not None:
        a = codecs._sampled_topk_indices(
            delta.copy(), 0.01, np.random.default_rng(42))
        orig_native, codecs._native = codecs._native, (lambda: None)
        try:
            b = codecs._sampled_topk_indices(
                delta.copy(), 0.01, np.random.default_rng(42))
        finally:
            codecs._native = orig_native
        np.testing.assert_array_equal(np.sort(a), np.sort(b))
        assert set(spikes).issubset(set(a.tolist()))

    # (b) the floor: a native scan that returns 0 entries (threshold
    # above every |delta| — NaN quantile / float-compare edges) must
    # fall back to the single argmax entry, exactly like numpy's
    # empty-selection branch
    class _ZeroLib:
        @staticmethod
        def geo_select_threshold(delta, n, thr, cap, idx):
            return 0

    d2 = np.zeros(128, np.float32)
    d2[7] = 1e-3
    orig_native, codecs._native = codecs._native, (lambda: _ZeroLib())
    try:
        floored = codecs._sampled_topk_indices(
            d2, 0.01, np.random.default_rng(0))
    finally:
        codecs._native = orig_native
    np.testing.assert_array_equal(floored, np.array([7], np.int64))
