"""Shared doc-drift engine + the metrics-doc checker.

One engine for "a source-extracted name set must match a markdown
catalog": extract name *templates* from call sites, expand the dynamic
ones through an explicit expansion table, and diff both directions
against the doc's backticked tokens.  ``docs/metrics.md`` (the PR 7
grep-audit that used to live inline in tests/test_metrics_doc.py) is
the first instance; ``docs/env-vars.md`` uses the same idea through the
config-drift checker.

The expansion table is the audit's teeth for dynamic names: a call site
whose name suffix is computed at runtime (``f"{self.node}.{action}s"``)
must list its concrete expansions here, so adding a new dynamic metric
without documenting what it can produce fails the audit by design.
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Tuple

from geomx_tpu.analysis.core import Checker, Finding, Project

_CALL = re.compile(r'system_(?:counter|gauge)\(\s*f?"([^"]+)"', re.S)


def _health_rules() -> Sequence[str]:
    from geomx_tpu.obs.health import RULES

    return list(RULES)


def metric_expansions() -> Dict[str, List[str]]:
    """Templates whose SUFFIX is computed at runtime -> the concrete
    names they can produce (each must be documented).  Imported by
    tests/test_metrics_doc.py, so the table has exactly one home."""
    return {
        "{self.po.node}.{action}s": ["party_folds", "party_unfolds"],
        "{postoffice.node}.wan_policy_{a}s": [
            "wan_policy_downshifts", "wan_policy_upshifts",
            "wan_policy_manuals"],
        "{self.node}.wan_bytes_{tag or 'vanilla'}": [
            "wan_bytes_vanilla", "wan_bytes_fp16", "wan_bytes_2bit",
            "wan_bytes_bsc", "wan_bytes_mpq"],
        "{self.node}.health_{r}_alerts": [
            f"health_{r}_alerts" for r in _health_rules()],
        # the flight recorder's pressure gauges (obs/flight.py
        # add_pressure): the van's send-queue / process-thread / reactor
        # probes are registered by the Postoffice, the merge-side trio
        # by attach_server_pressure
        "{self.node}.{name}": ["lock_wait_s", "lane_depth",
                               "van_sendq_depth", "codec_pool_busy",
                               "process_threads", "reactor_loop_lag_ms",
                               "reactor_fds"],
    }


def metric_templates(project: Project) -> List[Tuple[str, str]]:
    """(source rel, name template) for every system_counter/gauge call
    site in the package."""
    out: List[Tuple[str, str]] = []
    for f in project.files:
        for m in _CALL.finditer(f.text):
            out.append((f.rel, m.group(1)))
    return out


class MetricsDoc(Checker):
    name = "metrics-doc"
    description = ("every registered system metric is documented in "
                   "docs/metrics.md and every doc row has a live call "
                   "site")

    DOC = "metrics.md"

    def run(self, project: Project) -> List[Finding]:
        doc_path = project.docs_dir / self.DOC
        if not doc_path.exists():
            return []
        doc = doc_path.read_text()
        doc_rel = doc_path.relative_to(project.root).as_posix()
        templates = metric_templates(project)
        expansions = metric_expansions()
        findings: List[Finding] = []
        if not templates:
            findings.append(Finding(
                self.name, doc_rel, 1, f"{doc_rel}::audit::empty",
                "audit regex found no system_counter/system_gauge call "
                "sites — broken audit"))
            return findings
        for src, tpl in templates:
            # collapse {placeholders} to a marker FIRST — the node
            # expression itself contains dots ({self.po.node}.x)
            norm = re.sub(r"\{[^}]*\}", "\x00", tpl)
            if "." not in norm:
                findings.append(Finding(
                    self.name, src, 1, f"{src}::metric::noprefix:{tpl}",
                    f"metric {tpl!r} has no node prefix"))
                continue
            prefix, suffix = norm.split(".", 1)
            if "\x00" in suffix:
                if tpl not in expansions:
                    findings.append(Finding(
                        self.name, src, 1, f"{src}::metric::dynamic:{tpl}",
                        f"dynamic metric name {tpl!r} — add its "
                        "expansions to geomx_tpu/analysis/doc_drift.py "
                        "AND document them in docs/metrics.md"))
                    continue
                for name in expansions[tpl]:
                    if f"`{name}`" not in doc:
                        findings.append(Finding(
                            self.name, src, 1,
                            f"{src}::metric::missing:{name}",
                            f"{name} (expansion of {tpl!r}) not in "
                            "docs/metrics.md"))
                continue
            if prefix == "\x00":
                token = f"`{suffix}`"       # per-node: bare suffix
            else:
                # literal family prefix (global_shard<k>.*)
                token = ("`" + prefix.replace("\x00", "<k>") + "."
                         + suffix + "`")
            if token not in doc:
                findings.append(Finding(
                    self.name, src, 1, f"{src}::metric::missing:{token}",
                    f"{token} not in docs/metrics.md"))
        findings.extend(self._stale_rows(doc, doc_rel, templates,
                                         expansions))
        return findings

    def _stale_rows(self, doc: str, doc_rel: str,
                    templates: List[Tuple[str, str]],
                    expansions: Dict[str, List[str]]) -> List[Finding]:
        """The reverse direction, loosely: every per-node table row's
        name still has a matching call site (catches renames that
        orphan doc rows)."""
        tpls = [t for _, t in templates]
        expanded = [n for names in expansions.values() for n in names]
        rows = re.findall(r"^\| `([^`]+)` \|", doc, re.M)
        findings: List[Finding] = []
        if not rows:
            findings.append(Finding(
                self.name, doc_rel, 1, f"{doc_rel}::audit::norows",
                "no table rows parsed from docs/metrics.md"))
            return findings
        for name in rows:
            bare = name.replace("global_shard<k>.", "")
            if name in expanded or bare in expanded:
                continue
            if not any(t.endswith(f".{bare}") for t in tpls):
                findings.append(Finding(
                    self.name, doc_rel, 1, f"{doc_rel}::row::{name}",
                    f"doc row `{name}` has no call site — stale entry"))
        return findings
