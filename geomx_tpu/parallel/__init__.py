from geomx_tpu.parallel.mesh import make_mesh, named_sharding  # noqa: F401
from geomx_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from geomx_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
