"""CLI front end: ``python -m geomx_tpu.analysis``.

Exit codes: 0 = clean (modulo baseline), 1 = unsuppressed findings,
2 = usage / baseline-file error.  ``--baseline`` prints TOML skeleton
entries for the current unsuppressed findings (with a placeholder
reason that the loader REJECTS — paste, then justify or fix).
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from geomx_tpu.analysis import (CHECKERS, Baseline, BaselineError, Project,
                                repo_root, run_checkers, skeleton)
from geomx_tpu.analysis.baseline import DEFAULT_BASELINE


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m geomx_tpu.analysis",
        description="concurrency & protocol lint suite "
                    "(docs/static-analysis.md)")
    ap.add_argument("--check", action="append", metavar="NAME",
                    help="run only this checker (repeatable); "
                         "default: all")
    ap.add_argument("--baseline", action="store_true",
                    help="print baseline skeleton entries for the "
                         "current unsuppressed findings and exit 1 if "
                         "there are any")
    ap.add_argument("--baseline-file", default=None, metavar="PATH",
                    help=f"suppression file (default: <root>/"
                         f"{DEFAULT_BASELINE})")
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="project root (default: the repo this package "
                         "lives in)")
    ap.add_argument("--list", action="store_true",
                    help="list checkers and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name, cls in CHECKERS.items():
            print(f"{name:18s} {cls.description}")
        return 0

    root = pathlib.Path(args.root) if args.root else repo_root()
    try:
        project = Project(root)
        bl_path = (pathlib.Path(args.baseline_file) if args.baseline_file
                   else root / DEFAULT_BASELINE)
        bl = Baseline.load(bl_path)
        fresh, eaten, bl = run_checkers(project, args.check, bl)
    except BaselineError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    if args.baseline:
        if fresh:
            print(skeleton(fresh))
        print(f"# {len(fresh)} unsuppressed finding(s); "
              f"{len(eaten)} already baselined", file=sys.stderr)
        return 1 if fresh else 0

    for f in fresh:
        print(f.render())
    stale = [] if args.check else bl.unused()
    for s in stale:
        print(f"warning: stale baseline entry (matched nothing): "
              f"{s.checker} :: {s.key}", file=sys.stderr)
    checked = ", ".join(args.check) if args.check else "all checkers"
    print(f"{len(fresh)} finding(s) [{checked}], {len(eaten)} "
          f"baselined, {len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
